"""Tooling: parse_log, bandwidth, kill_mxnet, bi-lstm-sort.

reference: tools/parse_log.py (nightly gate consumer, test_all.sh:42-55),
tools/bandwidth/, tools/kill-mxnet.py, example/bi-lstm-sort/.
"""
import json
import pytest
import os
import signal
import subprocess
import sys
import time

import numpy as np

pytestmark = pytest.mark.slow

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(ROOT, "tools")

SAMPLE_LOG = """\
INFO:root:Epoch[0] Batch[20] speed=100.00 samples/s train: accuracy=0.5
INFO:root:Epoch[0] Train-accuracy=0.612000
INFO:root:Epoch[0] Time cost=10.500
INFO:root:Epoch[0] Validation-accuracy=0.650000
INFO:root:Epoch[1] Batch[20] speed=140.00 samples/s train: accuracy=0.8
INFO:root:Epoch[1] Train-accuracy=0.890000
INFO:root:Epoch[1] Time cost=9.100
INFO:root:Epoch[1] Validation-accuracy=0.915000
"""


def test_parse_log_table_and_gate(tmp_path):
    log = tmp_path / "train.log"
    log.write_text(SAMPLE_LOG)
    cli = os.path.join(TOOLS, "parse_log.py")
    r = subprocess.run([sys.executable, cli, str(log), "--format", "csv"],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    rows = r.stdout.strip().splitlines()
    assert rows[0].startswith("epoch,")
    assert "0.890000" in rows[2] and "0.915000" in rows[2]
    assert ",9.1," in rows[2] and "140.0" in rows[2]
    # gate passes at 0.9, fails at 0.92
    ok = subprocess.run([sys.executable, cli, str(log),
                         "--check-val", "accuracy:0.9"],
                        capture_output=True, text=True)
    assert ok.returncode == 0, ok.stderr
    bad = subprocess.run([sys.executable, cli, str(log),
                          "--check-val", "accuracy:0.92"],
                         capture_output=True, text=True)
    assert bad.returncode == 1


TELEMETRY_LOG = "\n".join([
    '{"type": "event", "kind": "batch_end", "epoch": 0, "nbatch": 0,'
    ' "duration_us": 100000, "batch_size": 32}',
    '{"type": "event", "kind": "batch_end", "epoch": 0, "nbatch": 1,'
    ' "duration_us": 100000, "batch_size": 32}',
    '{"type": "event", "kind": "epoch_end", "epoch": 0,'
    ' "time_cost_s": 10.5, "metrics": {"accuracy": 0.612}}',
    '{"type": "event", "kind": "speed", "epoch": 1, "nbatch": 20,'
    ' "samples_per_sec": 140.0}',
    '{"type": "event", "kind": "epoch_end", "epoch": 1,'
    ' "time_cost_s": 9.1, "metrics": {"accuracy": 0.89}}',
    '{"type": "span", "name": "kvstore.push", "ts_us": 1, "dur_us": 2,'
    ' "pid": 1, "tid": 1, "parent": null, "args": {}}',
    '{"type": "counter", "name": "io.batches", "labels": {},'
    ' "value": 2}',
]) + "\n"


def test_parse_log_telemetry_jsonl(tmp_path):
    """The telemetry jsonl event log parses into the same epoch table:
    epoch_end -> time/metrics, batch_end durations -> derived
    throughput, Speedometer speed events preferred when present."""
    sys.path.insert(0, TOOLS)
    import parse_log
    lines = TELEMETRY_LOG.splitlines()
    assert parse_log.looks_like_telemetry(lines)
    assert not parse_log.looks_like_telemetry(SAMPLE_LOG.splitlines())
    table = parse_log.parse_telemetry(lines)
    assert table[0]["train"]["accuracy"] == 0.612
    assert table[0]["time"] == 10.5
    # derived from batch_end: 32 samples / 0.1 s = 320 samples/s
    assert table[0]["speed"] == pytest.approx(320.0)
    # epoch 1 has an explicit speed event, which wins over derivation
    assert table[1]["speed"] == pytest.approx(140.0)
    assert table[1]["train"]["accuracy"] == 0.89

    # the CLI auto-detects the format and the gate works on it
    log = tmp_path / "telemetry.jsonl"
    log.write_text(TELEMETRY_LOG)
    cli = os.path.join(TOOLS, "parse_log.py")
    ok = subprocess.run([sys.executable, cli, str(log), "--format", "csv",
                         "--check-val", "accuracy:0.95"],
                        capture_output=True, text=True)
    # no validation metrics in this log -> gate reports missing (rc 2)
    assert ok.returncode == 2, (ok.stdout, ok.stderr)
    r = subprocess.run([sys.executable, cli, str(log), "--format", "csv"],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    rows = r.stdout.strip().splitlines()
    assert rows[0].startswith("epoch,")
    assert "0.890000" in rows[2] and "140.0" in rows[2]


SYNTHETIC_CRASH = {
    "type": "crash_report",
    "version": 1,
    "time_unix": 1754000000.0,
    "time": "2026-08-01T00:00:00+0000",
    "where": "module.fit",
    "pid": 4242,
    "argv": ["train.py"],
    "exception": {
        "type": "XlaRuntimeError",
        "message": "RESOURCE_EXHAUSTED: out of memory allocating 2.1GiB",
        "traceback": ["Traceback (most recent call last):\n",
                      "XlaRuntimeError: RESOURCE_EXHAUSTED\n"],
    },
    "ring": [
        {"kind": "executor.bind", "ts_us": 1000, "ctx": "tpu(0)",
         "arg_bytes": 1 << 30, "output_bytes": 1 << 20},
        {"kind": "span", "name": "op.Convolution", "ts_us": 2000,
         "dur_us": 90000},
        {"kind": "module.fit.batch", "ts_us": 200000, "epoch": 0,
         "nbatch": 0, "dur_us": 150000, "batch_size": 256},
        {"kind": "anomaly", "ts_us": 250000, "what": "gradient",
         "array": "fc1_weight", "step": 1},
        {"kind": "module.fit.batch", "ts_us": 400000, "epoch": 0,
         "nbatch": 1, "dur_us": 160000, "batch_size": 256},
    ],
    "metrics": {
        "counters": {"executor.jit_cache.hit": 18,
                     "executor.jit_cache.miss": 2},
        "gauges": {}, "histograms": {},
    },
    "memory": {"tpu(0)": {"live_bytes": 2147483648,
                          "peak_bytes": 3221225472,
                          "allocs": 900, "frees": 120}},
    "backend": "tpu",
    "devices": [{"id": 0, "platform": "tpu", "device_kind": "TPU v5e",
                 "process_index": 0}],
    "env": {"MXNET_FLIGHT_RECORDER": "1", "JAX_PLATFORMS": "tpu"},
}


def test_diagnose_crash_dump(tmp_path):
    """tools/diagnose.py renders a synthetic crash dump: exception,
    jit-cache rate, memory watermarks, first-anomaly, timeline."""
    dump = tmp_path / "mxnet_crash_4242_1.json"
    dump.write_text(json.dumps(SYNTHETIC_CRASH))
    cli = os.path.join(TOOLS, "diagnose.py")
    r = subprocess.run([sys.executable, cli, str(dump)],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    out = r.stdout
    assert "CRASH REPORT" in out
    assert "XlaRuntimeError" in out and "RESOURCE_EXHAUSTED" in out
    assert "module.fit" in out
    assert "90.0% hit rate" in out
    assert "tpu(0)" in out and "2.0 GiB" in out and "3.0 GiB" in out
    assert "FIRST: gradient 'fc1_weight' at step 1" in out
    assert "op.Convolution" in out                  # slowest span
    assert "module.fit.batch" in out                # recent timeline
    # missing file -> exit 2
    r2 = subprocess.run([sys.executable, cli, str(tmp_path / "nope.json")],
                        capture_output=True, text=True)
    assert r2.returncode == 2


DIAGNOSE_JSONL = "\n".join(
    [json.dumps({"type": "event", "kind": "batch_end", "epoch": 0,
                 "nbatch": i, "duration_us": 100000 + i * 20000,
                 "batch_size": 32}) for i in range(6)]
    + [json.dumps({"type": "event", "kind": "anomaly", "ts_us": 777,
                   "what": "output", "array": "softmax_output",
                   "step": 4}),
       json.dumps({"type": "span", "name": "op.FullyConnected",
                   "ts_us": 1, "dur_us": 5000, "pid": 1, "tid": 1,
                   "parent": None, "args": {}}),
       json.dumps({"type": "counter", "name": "executor.jit_cache.hit",
                   "labels": {}, "value": 6}),
       json.dumps({"type": "counter", "name": "executor.jit_cache.miss",
                   "labels": {}, "value": 2}),
       json.dumps({"type": "gauge", "name": "memory.live_bytes",
                   "labels": {"ctx": "cpu(0)"}, "value": 1048576.0}),
       json.dumps({"type": "gauge", "name": "memory.peak_bytes",
                   "labels": {"ctx": "cpu(0)"}, "value": 4194304.0}),
       json.dumps({"type": "histogram",
                   "name": "module.fit.batch.seconds", "labels": {},
                   "count": 6, "sum": 0.9, "min": 0.1, "max": 0.2,
                   "mean": 0.15})]) + "\n"


def test_diagnose_jsonl_health_report(tmp_path):
    """The jsonl path reports throughput trend (degrading here: batch
    durations grow), slowest ops, cache rate, memory, first anomaly."""
    log = tmp_path / "events.jsonl"
    log.write_text(DIAGNOSE_JSONL)
    cli = os.path.join(TOOLS, "diagnose.py")
    r = subprocess.run([sys.executable, cli, str(log)],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    out = r.stdout
    assert "TELEMETRY HEALTH REPORT" in out
    assert "DEGRADING" in out                  # durations trend up
    assert "75.0% hit rate" in out
    assert "cpu(0): live 1.0 MiB, peak 4.0 MiB" in out
    assert "FIRST: output 'softmax_output' at step 4" in out
    assert "op.FullyConnected" in out
    assert "batch time: mean 150.0 ms" in out


def test_mxlint_cli_subprocess(tmp_path):
    """The full mxlint CLI contract through a real interpreter: --check
    over the bundled corpus exits 0; a corrupt symbol JSON exits 1 and
    names the rule. (The fast in-process gate lives in
    tests/test_analysis.py; this one proves the console entry point.)"""
    cli = os.path.join(TOOLS, "mxlint.py")
    r = subprocess.run([sys.executable, cli, "--check"],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "models/resnet20" in r.stdout
    assert "0 error(s)" in r.stdout

    bad = tmp_path / "bad-symbol.json"
    bad.write_text(json.dumps({
        "nodes": [{"op": "_copy", "name": "c", "inputs": [[5, 0, 0]]}],
        "arg_nodes": [], "heads": [[0, 0, 0]]}))
    r2 = subprocess.run([sys.executable, cli, str(bad), "--json"],
                        capture_output=True, text=True)
    assert r2.returncode == 1, r2.stdout + r2.stderr
    doc = json.loads(r2.stdout[r2.stdout.index("{"):])
    assert doc["errors"] >= 1
    assert any(f["rule"] == "GV106" for f in doc["findings"])


def test_bandwidth_tool_local():
    r = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "bandwidth.py"),
         "--size-mb", "4", "--num-keys", "4", "--repeat", "3", "--cpu"],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-1500:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["metric"] == "kvstore_push_pull_bandwidth"
    assert out["gb_per_sec"] > 0
    assert out["num_workers"] == 1


def test_bandwidth_tool_dist_sync_2proc():
    env = dict(os.environ)
    env.pop("DMLC_NUM_WORKER", None)
    env.pop("DMLC_WORKER_ID", None)
    r = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "launch.py"), "-n", "2",
         sys.executable, os.path.join(TOOLS, "bandwidth.py"),
         "--kv-store", "dist_sync", "--size-mb", "2", "--num-keys", "4",
         "--repeat", "2"],
        capture_output=True, text=True, timeout=600, env=env, cwd=ROOT)
    assert r.returncode == 0, (r.stdout[-1500:], r.stderr[-1500:])
    rows = [json.loads(ln) for ln in r.stdout.splitlines()
            if ln.startswith("{")]
    assert len(rows) == 2
    assert all(row["num_workers"] == 2 for row in rows)
    assert all(row["gb_per_sec"] > 0 for row in rows)


def test_kill_mxnet_terminates_workers():
    env = dict(os.environ)
    env["DMLC_ROLE"] = "worker"
    marker = f"mx_kill_test_{os.getpid()}"
    victim = subprocess.Popen([sys.executable, "-c",
                               f"import time  # {marker}\n"
                               "time.sleep(300)"], env=env)
    try:
        time.sleep(0.3)
        # pattern-scoped: never sweep unrelated workers on this machine
        r = subprocess.run(
            [sys.executable, os.path.join(TOOLS, "kill_mxnet.py"), marker],
            capture_output=True, text=True, timeout=60)
        assert r.returncode == 0, r.stderr
        deadline = time.time() + 5
        while victim.poll() is None and time.time() < deadline:
            time.sleep(0.1)
        assert victim.poll() is not None, "worker not terminated"
        assert victim.returncode == -signal.SIGTERM
    finally:
        if victim.poll() is None:
            victim.kill()


def test_bi_lstm_sort_learns():
    sys.path.insert(0, os.path.join(ROOT, "examples"))
    import bi_lstm_sort
    train = bi_lstm_sort.make_batches(1280, 8, 8, 32)
    val = bi_lstm_sort.make_batches(256, 8, 8, 32, seed=9)
    import mxnet_tpu as mx
    net = bi_lstm_sort.build_symbol(8, 8, 48, 24)
    mod = mx.mod.Module(net, context=mx.cpu(),
                        label_names=("softmax_label",))
    mod.fit(train, num_epoch=5, initializer=mx.initializer.Xavier(),
            optimizer="adam", optimizer_params={"learning_rate": 0.01})
    acc = mod.score(val, "acc")[0][1]
    assert acc > 0.8, f"bi-lstm sort failed to learn: {acc}"


def test_diagnose_serving_section_from_live_jsonl(tmp_path):
    """ISSUE 8 satellite: a real serving session's jsonl log renders a
    'serving' section — p50/p99 from the exported latency-histogram
    buckets, occupancy/padding-waste from the counters, the queue-depth
    gauge, and the compiles-since-warmup steady-state flag."""
    import mxnet_tpu as mx
    from mxnet_tpu.serve import FakeClock

    mx.telemetry.reset()
    mx.telemetry.enable()
    try:
        data = mx.sym.var("data")
        fc = mx.sym.FullyConnected(data=data, num_hidden=4, name="dg1")
        sym = mx.sym.SoftmaxOutput(fc, name="softmax")
        mod = mx.mod.Module(sym, context=mx.cpu())
        mod.bind([("data", (4, 6))], [("softmax_label", (4,))],
                 for_training=False)
        mod.init_params(mx.initializer.Xavier())
        clock = FakeClock()
        server = mx.serve.serve(mod, ladder=[2, 4], start=False,
                                clock=clock, default_deadline_ms=20)
        for _ in range(3):
            server.submit({"data": np.zeros((1, 6), np.float32)})
        clock.advance(0.020)
        assert server.pump() == 1
        log = tmp_path / "serve.jsonl"
        mx.telemetry.jsonl.dump(str(log))
    finally:
        mx.telemetry.disable()

    cli = os.path.join(TOOLS, "diagnose.py")
    r = subprocess.run([sys.executable, cli, str(log)],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    out = r.stdout
    assert "serving:" in out
    assert "model default:" in out
    assert "p99" in out and "reqs" in out
    assert "75% occupancy" in out and "25.0% padding waste" in out
    assert "queue depth 0" in out
    assert "compiles since warmup: 0" in out
    assert "WARNING: serving is compiling" not in out
