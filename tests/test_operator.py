"""Operator tests (mirrors reference tests/python/unittest/test_operator.py
— numeric forward checks + finite-difference gradient checks via the
test_utils fixtures)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import (assert_almost_equal,
                                  check_numeric_gradient,
                                  check_symbolic_forward,
                                  check_symbolic_backward)


def test_elemwise_ops():
    a = mx.sym.var("a")
    b = mx.sym.var("b")
    a_np = np.random.rand(3, 4).astype(np.float32) + 0.5
    b_np = np.random.rand(3, 4).astype(np.float32) + 0.5
    check_symbolic_forward(a + b, [a_np, b_np], [a_np + b_np])
    check_symbolic_forward(a * b, [a_np, b_np], [a_np * b_np])
    check_symbolic_forward(a / b, [a_np, b_np], [a_np / b_np])
    g = np.ones((3, 4), dtype=np.float32)
    check_symbolic_backward(a * b, [a_np, b_np], [g], [b_np, a_np])
    check_symbolic_backward(a + b, [a_np, b_np], [g], [g, g])


def test_unary_math_ops():
    x = mx.sym.var("x")
    x_np = np.random.rand(4, 3).astype(np.float32) * 0.8 + 0.1
    cases = [
        (mx.sym.exp(x), np.exp(x_np)),
        (mx.sym.log(x), np.log(x_np)),
        (mx.sym.sqrt(x), np.sqrt(x_np)),
        (mx.sym.square(x), x_np ** 2),
        (mx.sym.tanh(x), np.tanh(x_np)),
        (mx.sym.sigmoid(x), 1 / (1 + np.exp(-x_np))),
        (mx.sym.relu(x - 0.5), np.maximum(x_np - 0.5, 0)),
        (mx.sym.abs(x - 0.5), np.abs(x_np - 0.5)),
    ]
    for sym, expect in cases:
        check_symbolic_forward(sym, {"x": x_np}, [expect], rtol=1e-4,
                               atol=1e-5)


def test_fullyconnected():
    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data=data, num_hidden=4, name="fc")
    d = np.random.rand(5, 3).astype(np.float32)
    w = np.random.rand(4, 3).astype(np.float32)
    b = np.random.rand(4).astype(np.float32)
    check_symbolic_forward(fc, {"data": d, "fc_weight": w, "fc_bias": b},
                           [d.dot(w.T) + b], rtol=1e-4)
    check_numeric_gradient(fc, {"data": d, "fc_weight": w, "fc_bias": b},
                           numeric_eps=1e-2, rtol=5e-2)


def test_convolution_forward():
    data = mx.sym.var("data")
    conv = mx.sym.Convolution(data=data, kernel=(3, 3), num_filter=2,
                              no_bias=True, name="conv")
    d = np.random.rand(1, 1, 5, 5).astype(np.float32)
    w = np.random.rand(2, 1, 3, 3).astype(np.float32)
    # direct correlation
    expect = np.zeros((1, 2, 3, 3), dtype=np.float32)
    for f in range(2):
        for i in range(3):
            for j in range(3):
                expect[0, f, i, j] = (d[0, 0, i:i + 3, j:j + 3] *
                                      w[f, 0]).sum()
    check_symbolic_forward(conv, {"data": d, "conv_weight": w}, [expect],
                           rtol=1e-4)


def test_convolution_grad():
    data = mx.sym.var("data")
    conv = mx.sym.Convolution(data=data, kernel=(2, 2), num_filter=2,
                              stride=(1, 1), name="conv")
    d = np.random.rand(2, 2, 4, 4).astype(np.float32)
    w = np.random.rand(2, 2, 2, 2).astype(np.float32)
    b = np.random.rand(2).astype(np.float32)
    check_numeric_gradient(conv, {"data": d, "conv_weight": w,
                                  "conv_bias": b},
                           numeric_eps=1e-2, rtol=5e-2)


def test_pooling():
    data = mx.sym.var("data")
    d = np.random.rand(1, 1, 4, 4).astype(np.float32)
    pool = mx.sym.Pooling(data=data, kernel=(2, 2), stride=(2, 2),
                          pool_type="max")
    expect = d.reshape(1, 1, 2, 2, 2, 2).max(axis=(3, 5))
    check_symbolic_forward(pool, {"data": d}, [expect])
    avg = mx.sym.Pooling(data=data, kernel=(2, 2), stride=(2, 2),
                         pool_type="avg")
    expect_avg = d.reshape(1, 1, 2, 2, 2, 2).mean(axis=(3, 5))
    check_symbolic_forward(avg, {"data": d}, [expect_avg], rtol=1e-5)
    gpool = mx.sym.Pooling(data=data, global_pool=True, kernel=(2, 2),
                           pool_type="avg")
    check_symbolic_forward(gpool, {"data": d},
                           [d.mean(axis=(2, 3), keepdims=True)], rtol=1e-5)


def test_activation_grads():
    data = mx.sym.var("data")
    d = np.random.rand(3, 4).astype(np.float32) * 2 - 1
    for act in ["relu", "sigmoid", "tanh", "softrelu"]:
        sym = mx.sym.Activation(data=data, act_type=act)
        check_numeric_gradient(sym, {"data": d + 2.0}, numeric_eps=1e-2,
                               rtol=5e-2)


def test_leaky_relu():
    data = mx.sym.var("data")
    d = np.array([[-1.0, 2.0], [-3.0, 0.5]], dtype=np.float32)
    sym = mx.sym.LeakyReLU(data=data, act_type="leaky", slope=0.1)
    expect = np.where(d > 0, d, 0.1 * d)
    check_symbolic_forward(sym, {"data": d}, [expect])
    elu = mx.sym.LeakyReLU(data=data, act_type="elu", slope=0.5)
    expect_elu = np.where(d > 0, d, 0.5 * (np.exp(d) - 1))
    check_symbolic_forward(elu, {"data": d}, [expect_elu], rtol=1e-5)


def test_batchnorm_training_stats():
    data = mx.sym.var("data")
    bn = mx.sym.BatchNorm(data=data, fix_gamma=False, momentum=0.9,
                          eps=1e-5, name="bn")
    d = np.random.rand(8, 3, 4, 4).astype(np.float32) * 5
    ex = bn.simple_bind(ctx=mx.cpu(), data=d.shape)
    ex.arg_dict["data"][:] = d
    ex.arg_dict["bn_gamma"][:] = 1
    ex.arg_dict["bn_beta"][:] = 0
    out = ex.forward(is_train=True)[0].asnumpy()
    mean = d.mean(axis=(0, 2, 3))
    var = d.var(axis=(0, 2, 3))
    expect = (d - mean[None, :, None, None]) / \
        np.sqrt(var[None, :, None, None] + 1e-5)
    assert_almost_equal(out, expect, rtol=1e-3, atol=1e-4)
    # moving stats updated: 0.9 * 0 + 0.1 * mean
    assert_almost_equal(ex.aux_dict["bn_moving_mean"], 0.1 * mean,
                        rtol=1e-3, atol=1e-5)
    # inference path uses moving stats
    ex.aux_dict["bn_moving_mean"][:] = mean
    ex.aux_dict["bn_moving_var"][:] = var
    out_inf = ex.forward(is_train=False)[0].asnumpy()
    assert_almost_equal(out_inf, expect, rtol=1e-3, atol=1e-4)


def test_dropout():
    data = mx.sym.var("data")
    do = mx.sym.Dropout(data=data, p=0.5, name="do")
    d = np.ones((100, 100), dtype=np.float32)
    ex = do.simple_bind(ctx=mx.cpu(), data=d.shape)
    ex.arg_dict["data"][:] = d
    out_inf = ex.forward(is_train=False)[0].asnumpy()
    assert_almost_equal(out_inf, d)  # identity at inference
    out_tr = ex.forward(is_train=True)[0].asnumpy()
    frac = (out_tr == 0).mean()
    assert 0.3 < frac < 0.7
    # kept elements scaled by 1/keep
    kept = out_tr[out_tr != 0]
    assert_almost_equal(kept, np.full_like(kept, 2.0), rtol=1e-5)


def test_softmax_output_grad():
    data = mx.sym.var("data")
    sm = mx.sym.SoftmaxOutput(data=data, name="softmax", grad_scale=2.0)
    d = np.random.rand(4, 5).astype(np.float32)
    label = np.array([1, 0, 4, 2], dtype=np.float32)
    ex = sm.simple_bind(ctx=mx.cpu(), data=d.shape)
    ex.arg_dict["data"][:] = d
    ex.arg_dict["softmax_label"][:] = label
    ex.forward(is_train=True)
    ex.backward()
    prob = ex.outputs[0].asnumpy()
    onehot = np.eye(5, dtype=np.float32)[label.astype(int)]
    assert_almost_equal(ex.grad_dict["data"], 2.0 * (prob - onehot),
                        rtol=1e-5)


def test_regression_outputs():
    data = mx.sym.var("data")
    label = mx.sym.var("label")
    d = np.random.rand(4, 3).astype(np.float32)
    l = np.random.rand(4, 3).astype(np.float32)
    lin = mx.sym.LinearRegressionOutput(data=data, label=label)
    ex = lin.bind(mx.cpu(), args={"data": mx.nd.array(d),
                                  "label": mx.nd.array(l)},
                  args_grad={"data": mx.nd.zeros(d.shape)},
                  grad_req={"data": "write", "label": "null"})
    ex.forward(is_train=True)
    ex.backward()
    assert_almost_equal(ex.outputs[0], d)
    assert_almost_equal(ex.grad_dict["data"], (d - l) / 3, rtol=1e-5)
    log = mx.sym.LogisticRegressionOutput(data=data, label=label)
    out = log.bind(mx.cpu(), args={"data": mx.nd.array(d),
                                   "label": mx.nd.array(l)}).forward()
    assert_almost_equal(out[0], 1 / (1 + np.exp(-d)), rtol=1e-5)


def test_blockgrad_makeloss():
    data = mx.sym.var("data")
    d = np.random.rand(3, 3).astype(np.float32)
    bg = mx.sym.BlockGrad(data)
    ex = bg.bind(mx.cpu(), args={"data": mx.nd.array(d)},
                 args_grad={"data": mx.nd.ones(d.shape)})
    ex.forward(is_train=True)
    ex.backward([mx.nd.ones(d.shape)])
    assert_almost_equal(ex.grad_dict["data"], np.zeros_like(d))
    ml = mx.sym.MakeLoss(mx.sym.square(data), grad_scale=3.0)
    ex2 = ml.bind(mx.cpu(), args={"data": mx.nd.array(d)},
                  args_grad={"data": mx.nd.zeros(d.shape)})
    ex2.forward(is_train=True)
    ex2.backward()
    assert_almost_equal(ex2.grad_dict["data"], 3.0 * 2 * d, rtol=1e-5)


def test_concat_slicechannel():
    a = mx.sym.var("a")
    b = mx.sym.var("b")
    cat = mx.sym.Concat(a, b, dim=1, name="cat")
    a_np = np.random.rand(2, 3).astype(np.float32)
    b_np = np.random.rand(2, 4).astype(np.float32)
    check_symbolic_forward(cat, {"a": a_np, "b": b_np},
                           [np.concatenate([a_np, b_np], axis=1)])
    g = np.random.rand(2, 7).astype(np.float32)
    check_symbolic_backward(cat, {"a": a_np, "b": b_np}, [g],
                            {"a": g[:, :3], "b": g[:, 3:]})
    data = mx.sym.var("data")
    sl = mx.sym.SliceChannel(data, num_outputs=2, axis=1)
    d = np.random.rand(2, 6).astype(np.float32)
    check_symbolic_forward(sl, {"data": d}, [d[:, :3], d[:, 3:]])


def test_reshape_transpose_ops():
    data = mx.sym.var("data")
    d = np.arange(24).reshape(2, 3, 4).astype(np.float32)
    check_symbolic_forward(mx.sym.Reshape(data, shape=(2, 12)),
                           {"data": d}, [d.reshape(2, 12)])
    check_symbolic_forward(mx.sym.Reshape(data, shape=(0, -1)),
                           {"data": d}, [d.reshape(2, 12)])
    check_symbolic_forward(mx.sym.transpose(data, axes=(1, 0, 2)),
                           {"data": d}, [d.transpose(1, 0, 2)])
    check_symbolic_forward(mx.sym.Flatten(data), {"data": d},
                           [d.reshape(2, 12)])
    check_symbolic_forward(mx.sym.expand_dims(data, axis=1),
                           {"data": d}, [d[:, None]])
    check_symbolic_forward(mx.sym.slice_axis(data, axis=2, begin=1, end=3),
                           {"data": d}, [d[:, :, 1:3]])


def test_broadcast_reduce():
    data = mx.sym.var("data")
    d = np.random.rand(2, 3, 4).astype(np.float32)
    check_symbolic_forward(mx.sym.sum(data, axis=1), {"data": d},
                           [d.sum(axis=1)], rtol=1e-5)
    check_symbolic_forward(mx.sym.mean(data, axis=(0, 2)), {"data": d},
                           [d.mean(axis=(0, 2))], rtol=1e-5)
    check_symbolic_forward(mx.sym.max(data, axis=2, keepdims=True),
                           {"data": d}, [d.max(axis=2, keepdims=True)])
    check_symbolic_forward(mx.sym.norm(data), {"data": d},
                           [np.asarray(np.sqrt((d ** 2).sum()))], rtol=1e-4)
    check_symbolic_forward(mx.sym.argmax(data, axis=1), {"data": d},
                           [d.argmax(axis=1).astype(np.float32)])


def test_embedding_take():
    data = mx.sym.var("data")
    emb = mx.sym.Embedding(data=data, input_dim=10, output_dim=4,
                           name="emb")
    idx = np.array([[1, 2], [3, 4]], dtype=np.float32)
    w = np.random.rand(10, 4).astype(np.float32)
    check_symbolic_forward(emb, {"data": idx, "emb_weight": w},
                           [w[idx.astype(int)]])
    arg_shapes, out_shapes, _ = emb.infer_shape(data=(2, 2))
    assert out_shapes == [(2, 2, 4)]
    assert dict(zip(emb.list_arguments(), arg_shapes))["emb_weight"] == \
        (10, 4)


def test_where_pick():
    cond = mx.sym.var("cond")
    x = mx.sym.var("x")
    y = mx.sym.var("y")
    w = mx.sym.where(cond, x, y)
    c_np = np.array([[1, 0], [0, 1]], dtype=np.float32)
    x_np = np.ones((2, 2), dtype=np.float32)
    y_np = np.zeros((2, 2), dtype=np.float32)
    check_symbolic_forward(w, {"cond": c_np, "x": x_np, "y": y_np}, [c_np])
    data = mx.sym.var("data")
    index = mx.sym.var("index")
    p = mx.sym.pick(data, index, axis=1)
    d = np.random.rand(3, 4).astype(np.float32)
    i = np.array([0, 2, 1], dtype=np.float32)
    check_symbolic_forward(p, {"data": d, "index": i},
                           [d[np.arange(3), i.astype(int)]])


def test_sequence_ops():
    data = mx.sym.var("data")
    d = np.random.rand(4, 2, 3).astype(np.float32)  # (T, N, C)
    sl = mx.sym.SequenceLast(data)
    check_symbolic_forward(sl, {"data": d}, [d[-1]])
    sr = mx.sym.SequenceReverse(data)
    check_symbolic_forward(sr, {"data": d}, [d[::-1]])
    seq = mx.sym.var("sequence_length")
    sm = mx.sym.SequenceMask(data, seq, use_sequence_length=True, value=0.0)
    lens = np.array([2, 4], dtype=np.float32)
    expect = d.copy()
    expect[2:, 0] = 0
    check_symbolic_forward(sm, {"data": d, "sequence_length": lens},
                           [expect])


def test_upsampling_nearest():
    data = mx.sym.var("data")
    up = mx.sym.UpSampling(data, scale=2, sample_type="nearest",
                           num_args=1)
    d = np.random.rand(1, 2, 3, 3).astype(np.float32)
    expect = d.repeat(2, axis=2).repeat(2, axis=3)
    check_symbolic_forward(up, {"data": d}, [expect])


def test_swapaxis_pad():
    data = mx.sym.var("data")
    d = np.random.rand(2, 3, 4).astype(np.float32)
    check_symbolic_forward(mx.sym.SwapAxis(data, dim1=0, dim2=2),
                           {"data": d}, [d.transpose(2, 1, 0)])
    d4 = np.random.rand(1, 1, 2, 2).astype(np.float32)
    pad = mx.sym.Pad(mx.sym.var("x"), mode="constant",
                     pad_width=(0, 0, 0, 0, 1, 1, 1, 1),
                     constant_value=0.0)
    expect = np.pad(d4, ((0, 0), (0, 0), (1, 1), (1, 1)))
    check_symbolic_forward(pad, {"x": d4}, [expect])


def test_l2_normalization_instancenorm():
    data = mx.sym.var("data")
    d = np.random.rand(2, 3, 4, 4).astype(np.float32) + 0.1
    l2 = mx.sym.L2Normalization(data, mode="instance")
    norm = np.sqrt((d.reshape(2, -1) ** 2).sum(axis=1) + 1e-10)
    expect = d / norm[:, None, None, None]
    check_symbolic_forward(l2, {"data": d}, [expect], rtol=1e-4)
    inorm = mx.sym.InstanceNorm(mx.sym.var("data"), name="in")
    gamma = np.ones(3, dtype=np.float32)
    beta = np.zeros(3, dtype=np.float32)
    mean = d.mean(axis=(2, 3), keepdims=True)
    var = d.var(axis=(2, 3), keepdims=True)
    expect_in = (d - mean) / np.sqrt(var + 1e-3)
    check_symbolic_forward(inorm, {"data": d, "in_gamma": gamma,
                                   "in_beta": beta}, [expect_in], rtol=1e-3,
                           atol=1e-4)


def test_optimizer_update_ops():
    w = mx.nd.array(np.ones(4, dtype=np.float32))
    g = mx.nd.array(np.full(4, 0.5, dtype=np.float32))
    mx.nd.sgd_update(w, g, lr=0.1, wd=0.0)
    assert_almost_equal(w, np.full(4, 0.95), rtol=1e-6)
    mom = mx.nd.zeros((4,))
    mx.nd.sgd_mom_update(w, g, mom, lr=0.1, momentum=0.9, wd=0.0)
    assert_almost_equal(w, np.full(4, 0.90), rtol=1e-5)
    assert_almost_equal(mom, np.full(4, -0.05), rtol=1e-5)


def test_sampling_ops():
    out = mx.nd.random_uniform(low=0, high=1, shape=(1000,))
    arr = out.asnumpy()
    assert arr.min() >= 0 and arr.max() <= 1
    assert abs(arr.mean() - 0.5) < 0.05
    n = mx.nd.random_normal(loc=2.0, scale=0.5, shape=(2000,)).asnumpy()
    assert abs(n.mean() - 2.0) < 0.1
    assert abs(n.std() - 0.5) < 0.1


def test_smooth_l1():
    data = mx.sym.var("data")
    sl = mx.sym.smooth_l1(data, scalar=1.0)
    d = np.array([-2.0, -0.5, 0.0, 0.5, 2.0], dtype=np.float32)
    expect = np.where(np.abs(d) < 1, 0.5 * d * d, np.abs(d) - 0.5)
    check_symbolic_forward(sl, {"data": d}, [expect])
