"""SSD end-to-end + detection augmenters.

Gates the last uncovered BASELINE config (reference: example/ssd/): the
MultiBox op trio driven by a real training loop on synthetic shapes, and
the box-aware augmenters (reference: image_det_aug_default.cc:1-667).
"""
import os
import sys
import types

import numpy as np
import pytest

import mxnet_tpu as mx

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))
from examples.ssd import data as shapes_data  # noqa: E402
from examples.ssd import symbol as ssd_symbol  # noqa: E402
from examples.ssd import train as ssd_train  # noqa: E402

pytestmark = pytest.mark.slow


# ------------------------------------------------------------- augmenters
def test_det_flip_box_math():
    img = np.zeros((10, 20, 3), np.uint8)
    img[:, :10] = 255
    label = np.array([[0, 0.1, 0.2, 0.4, 0.6],
                      [-1, 0, 0, 0, 0]], np.float32)
    aug = mx.image.DetHorizontalFlipAug(1.0)
    out, lab = aug(img, label)
    assert np.allclose(lab[0], [0, 0.6, 0.2, 0.9, 0.6], atol=1e-6)
    assert lab[1, 0] == -1
    assert np.asarray(out)[:, 10:].max() == 255  # image mirrored too


def test_det_crop_keeps_centers_and_renormalizes():
    np.random.seed(0)
    import random as pyrandom
    pyrandom.seed(0)
    img = np.random.randint(0, 255, (40, 40, 3)).astype(np.uint8)
    label = np.array([[1, 0.4, 0.4, 0.6, 0.6]], np.float32)
    aug = mx.image.DetRandomCropAug(min_object_covered=0.5,
                                    area_range=(0.5, 0.9))
    for _ in range(5):
        out, lab = aug(img, label)
        if lab[0, 0] >= 0:
            assert 0.0 <= lab[0, 1] < lab[0, 3] <= 1.0
            assert 0.0 <= lab[0, 2] < lab[0, 4] <= 1.0


def test_det_pad_shrinks_boxes():
    import random as pyrandom
    pyrandom.seed(1)
    img = np.full((20, 20, 3), 200, np.uint8)
    label = np.array([[0, 0.0, 0.0, 1.0, 1.0]], np.float32)
    aug = mx.image.DetRandomPadAug(area_range=(1.5, 2.0))
    out, lab = aug(img, label)
    w = lab[0, 3] - lab[0, 1]
    h = lab[0, 4] - lab[0, 2]
    assert w < 1.0 and h < 1.0          # box shrank on the canvas
    assert w * h > 0.3                  # but not degenerately


def test_det_iter_shapes_and_padding():
    imgs, labs = shapes_data.make_shapes_dataset(10, size=48)
    it = mx.image.ImageDetIter(4, (3, 48, 48), imgs, labs, max_objects=3)
    b = next(it)
    assert b.data[0].shape == (4, 3, 48, 48)
    assert b.label[0].shape == (4, 3, 5)
    lab = b.label[0].asnumpy()
    valid = lab[:, :, 0] >= 0
    assert valid.any()
    assert (lab[~valid] == -1).all()


# ------------------------------------------------------------- end to end
def test_ssd_trains_and_detects():
    """Loss must fall and decoded detections must localize objects on the
    training distribution (synthetic shapes)."""
    args = types.SimpleNamespace(epochs=6, batch_size=16, num_images=96,
                                 data_size=96, width=16, lr=0.02,
                                 log_every=50)
    train_iter, _ = ssd_train.build_iters(args,
                                          rng=np.random.RandomState(1))
    net = ssd_symbol.get_train_symbol(num_classes=2, width=args.width)
    mod = mx.mod.Module(net, data_names=("data",), label_names=("label",),
                        context=mx.cpu())
    metric = ssd_train.MultiBoxMetric()
    first_ce, last_ce = [], []

    class Grab:
        def __init__(self, store):
            self.store = store

        def __call__(self, param):
            names, vals = param.eval_metric.get()
            self.store.append(vals[0])

    for epoch in range(args.epochs):
        train_iter.reset()
        metric.reset()
        for batch in train_iter:
            if not mod.binded:
                mod.bind(train_iter.provide_data, train_iter.provide_label,
                         for_training=True)
                mod.init_params(mx.initializer.Xavier())
                mod.init_optimizer(optimizer="sgd", optimizer_params={
                    "learning_rate": args.lr, "momentum": 0.9, "wd": 5e-4})
            mod.forward_backward(batch)
            mod.update()
            mod.update_metric(metric, batch.label)
        ce = metric.get()[1][0]
        (first_ce if epoch == 0 else last_ce).append(ce)
    assert last_ce[-1] < 0.6 * first_ce[0], (first_ce, last_ce)

    # detection sanity on the training distribution
    imgs, labs = shapes_data.make_shapes_dataset(
        4, size=args.data_size, rng=np.random.RandomState(9))
    dets = ssd_train.detect(mod, args, imgs)
    assert dets.shape[0] == 4 and dets.shape[2] == 6
    hits = 0
    for det, lab in zip(dets, labs):
        kept = det[det[:, 0] >= 0]
        if not len(kept):
            continue
        top = kept[np.argsort(-kept[:, 1])][: len(lab)]
        for gt in lab:
            gx1, gy1, gx2, gy2 = gt[1:5]
            for row in top:
                x1, y1, x2, y2 = row[2:6]
                ix = max(0, min(x2, gx2) - max(x1, gx1))
                iy = max(0, min(y2, gy2) - max(y1, gy1))
                inter = ix * iy
                union = (x2 - x1) * (y2 - y1) + \
                    (gx2 - gx1) * (gy2 - gy1) - inter
                if union > 0 and inter / union > 0.3:
                    hits += 1
                    break
    total_gt = sum(len(l) for l in labs)
    assert hits >= total_gt * 0.5, (hits, total_gt)
