"""Profiler (reference: tests/python/unittest/test_profiler.py —
set_config/run/stop writes a trace; per-op names flow into it via the
executor's jax.named_scope wrapping AND the telemetry span tracer, whose
chrome://tracing JSON dump_profile() now emits like MXDumpProfile)."""
import glob
import json
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry as tm


@pytest.fixture(autouse=True)
def _clean_telemetry():
    tm.disable()
    tm.reset()
    yield
    tm.disable()
    tm.reset()


def test_profiler_trace_roundtrip(tmp_path):
    mx.profiler.profiler_set_config(mode="all",
                                    filename=str(tmp_path / "prof.json"))
    mx.profiler.profiler_set_state("run")
    x = mx.sym.var("data")
    out = mx.sym.FullyConnected(x, num_hidden=4, name="proffc")
    exe = out.simple_bind(ctx=mx.cpu(), data=(4, 8))
    exe.arg_dict["data"][:] = np.random.rand(4, 8).astype("f")
    exe.forward(is_train=False)
    exe.outputs[0].asnumpy()
    mx.profiler.profiler_set_state("stop")
    path = mx.profiler.dump_profile()
    # the chrome trace JSON at the configured filename...
    assert path == str(tmp_path / "prof.json") and os.path.isfile(path)
    doc = json.load(open(path))
    assert doc["traceEvents"], "trace is empty"
    names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert "executor.bind" in names
    assert any(n.startswith("op.") for n in names)
    # ...plus the JAX xplane trace dir referenced in its metadata
    trace_dir = doc["otherData"]["jax_trace_dir"]
    assert os.path.isdir(trace_dir)
    files = glob.glob(os.path.join(trace_dir, "**", "*"), recursive=True)
    assert any(os.path.isfile(f) for f in files), "no trace artifacts"


def test_profiler_full_step_trace_schema(tmp_path):
    """ISSUE 1 acceptance: run -> train 2 batches -> dump_profile()
    yields schema-valid chrome://tracing JSON containing spans for
    compile, op execution, kvstore push/pull, and data loading."""
    mx.profiler.profiler_set_config(mode="all",
                                    filename=str(tmp_path / "fit.json"))
    mx.profiler.profiler_set_state("run")
    X = np.random.rand(8, 10).astype("f")
    Y = (np.random.rand(8) * 3).astype("f")
    it = mx.io.NDArrayIter(X, Y, batch_size=4)    # 2 batches
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=3),
        name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=1, kvstore="dist_sync",
            optimizer_params={"learning_rate": 0.1})
    path = mx.profiler.dump_profile()
    doc = json.load(open(path))
    events = doc["traceEvents"]
    for e in events:                       # chrome trace event schema
        assert isinstance(e["name"], str) and e["name"]
        assert e["ph"] in ("X", "M", "i")
        assert isinstance(e["pid"], int)
        if e["ph"] == "X":
            assert isinstance(e["tid"], int)
            assert isinstance(e["ts"], int)
            assert isinstance(e["dur"], int) and e["dur"] >= 0
    names = {e["name"] for e in events if e["ph"] == "X"}
    assert "executor.compile" in names         # compile
    assert any(n.startswith("op.") for n in names)   # op execution
    assert "kvstore.push" in names and "kvstore.pull" in names
    assert "io.next" in names                  # data loading
    assert "module.fit.batch" in names


def test_dump_profile_without_trace_returns_filename(tmp_path):
    """Satellite fix: dump_profile() with no trace ever started must
    return the configured filename (a real written file), never None."""
    target = str(tmp_path / "cold.json")
    mx.profiler.profiler_set_config(filename=target)
    path = mx.profiler.dump_profile()
    assert path == target
    assert os.path.isfile(path)
    doc = json.load(open(path))
    assert "traceEvents" in doc


def test_profiler_rejects_bad_state():
    with pytest.raises(ValueError):
        mx.profiler.profiler_set_state("pause")
