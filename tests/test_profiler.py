"""Profiler (reference: tests/python/unittest/test_profiler.py —
set_config/run/stop writes a trace; per-op names flow into it via the
executor's jax.named_scope wrapping)."""
import glob
import os

import numpy as np

import mxnet_tpu as mx


def test_profiler_trace_roundtrip(tmp_path):
    mx.profiler.profiler_set_config(mode="all",
                                    filename=str(tmp_path / "prof.json"))
    mx.profiler.profiler_set_state("run")
    x = mx.sym.var("data")
    out = mx.sym.FullyConnected(x, num_hidden=4, name="proffc")
    exe = out.simple_bind(ctx=mx.cpu(), data=(4, 8))
    exe.arg_dict["data"][:] = np.random.rand(4, 8).astype("f")
    exe.forward(is_train=False)
    exe.outputs[0].asnumpy()
    mx.profiler.profiler_set_state("stop")
    trace_dir = mx.profiler.dump_profile()
    assert trace_dir and os.path.isdir(trace_dir)
    files = glob.glob(os.path.join(trace_dir, "**", "*"), recursive=True)
    assert any(os.path.isfile(f) for f in files), "no trace artifacts"


def test_profiler_rejects_bad_state():
    import pytest
    with pytest.raises(ValueError):
        mx.profiler.profiler_set_state("pause")
