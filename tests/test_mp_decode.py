"""Multiprocess decode pipeline (mp_decode.py + _decode_worker.py).

The MP pipeline must (a) produce byte-identical batches to the
thread-pool ImageIter for the deterministic augment chain, (b) handle
epochs/shuffle/padding, and (c) survive worker teardown. Reference
analog: the OMP-parallel ImageRecordIOParser2
(src/io/iter_image_recordio_2.cc:28-595) whose output feeds the same
BatchLoader contract.
"""
import os
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio

cv2 = pytest.importorskip("cv2")

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "tools"))


def _make_pack(tmp_path, n=48, size=(40, 48)):
    import im2rec
    prefix = str(tmp_path / "toy")
    rng = np.random.RandomState(0)
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    for i in range(n):
        img = rng.randint(0, 255, size + (3,), dtype=np.uint8)
        buf = im2rec._encode(img, quality=90)
        rec.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i % 10), i, 0), buf))
    rec.close()
    return prefix


def test_mp_matches_thread_pipeline(tmp_path):
    prefix = _make_pack(tmp_path)
    kw = dict(data_shape=(3, 32, 32), batch_size=8, mean_r=10, mean_g=20,
              mean_b=30, std_r=2, std_g=2, std_b=2, prefetch=False)
    it_mp = mx.image.ImageRecordIter(prefix + ".rec",
                                     path_imgidx=prefix + ".idx",
                                     num_workers=2, **kw)
    it_th = mx.image.ImageRecordIter(prefix + ".rec",
                                     path_imgidx=prefix + ".idx",
                                     num_workers=0, **kw)
    assert type(it_mp).__name__ == "MPImageRecordIter"
    n = 0
    for b_mp, b_th in zip(it_mp, it_th):
        np.testing.assert_allclose(b_mp.data[0].asnumpy(),
                                   b_th.data[0].asnumpy(), atol=1e-5)
        np.testing.assert_allclose(b_mp.label[0].asnumpy(),
                                   b_th.label[0].asnumpy())
        n += 1
    assert n == 6
    it_mp.close()


def test_mp_padding_and_epochs(tmp_path):
    prefix = _make_pack(tmp_path, n=21)
    it = mx.image.ImageRecordIter(prefix + ".rec", data_shape=(3, 16, 16),
                                  batch_size=8, num_workers=2,
                                  prefetch=False)
    batches = list(it)
    assert len(batches) == 3
    assert batches[-1].pad == 3
    total = sum(b.data[0].shape[0] - b.pad for b in batches)
    assert total == 21
    it.reset()
    batches2 = list(it)
    assert len(batches2) == 3
    np.testing.assert_allclose(batches[0].data[0].asnumpy(),
                               batches2[0].data[0].asnumpy())
    it.close()


def test_mp_shuffle_covers_all_labels(tmp_path):
    prefix = _make_pack(tmp_path, n=32)
    it = mx.image.ImageRecordIter(prefix + ".rec", data_shape=(3, 16, 16),
                                  batch_size=8, shuffle=True,
                                  num_workers=2, prefetch=False)
    ep1 = np.concatenate([b.label[0].asnumpy() for b in it])
    it.reset()
    ep2 = np.concatenate([b.label[0].asnumpy() for b in it])
    # both epochs see every record exactly once, in different orders
    ref = np.sort(np.arange(32) % 10).astype(np.float32)
    assert (np.sort(ep1) == ref).all() and (np.sort(ep2) == ref).all()
    assert not (ep1 == ep2).all()
    it.close()


def test_mp_error_recovery_no_desync(tmp_path):
    """A worker error mid-epoch leaves replies partially read; reset()
    must drain each stream exactly so the next epoch's slots aren't
    copied before the worker confirmed writing them (ADVICE r4)."""
    import im2rec
    prefix = str(tmp_path / "bad")
    rng = np.random.RandomState(1)
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    for i in range(24):
        if i == 17:  # lands in a mid-shard position of the last batch
            payload = b"not an image"
        else:
            img = rng.randint(0, 255, (40, 48, 3), dtype=np.uint8)
            payload = im2rec._encode(img, quality=90)
        rec.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i % 10), i, 0), payload))
    rec.close()

    from mxnet_tpu.mp_decode import MPImageRecordIter
    it = MPImageRecordIter(prefix + ".rec", data_shape=(3, 16, 16),
                           batch_size=8, path_imgidx=prefix + ".idx",
                           num_workers=2)
    good = [it.next().data[0].asnumpy() for _ in range(2)]
    with pytest.raises(mx.base.MXNetError, match="decode worker"):
        it.next()                      # batch 3 carries the bad record
    it.reset()                         # exact per-stream drain
    again = [it.next().data[0].asnumpy() for _ in range(2)]
    for a, b in zip(good, again):      # no stale-slot reads after recovery
        np.testing.assert_allclose(a, b)
    it.close()


def test_mp_offset_scan_matches_idx(tmp_path):
    from mxnet_tpu.mp_decode import scan_record_offsets
    prefix = _make_pack(tmp_path, n=16)
    scanned = scan_record_offsets(prefix + ".rec")
    with open(prefix + ".idx") as f:
        from_idx = [int(l.split("\t")[1]) for l in f if l.strip()]
    assert scanned == sorted(from_idx)
    assert len(scanned) == 16
