"""Model-zoo symbol checks (shape inference is cheap; forwards are slow).

Reference analog: tests/python/unittest/test_symbol.py + the example
zoo's implicit coverage via example runs.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.models import inception_v3


def test_inception_v3_shapes():
    """299x299 in, (N, classes) out, published parameter budget, and
    reference checkpoint naming (reference:
    example/image-classification/symbols/inception-v3.py:1)."""
    net = inception_v3.get_symbol(num_classes=1000)
    arg_shapes, out_shapes, _ = net.infer_shape(data=(2, 3, 299, 299))
    assert out_shapes == [(2, 1000)]
    names = net.list_arguments()
    total = sum(int(np.prod(s)) for s in arg_shapes)
    assert 23_000_000 < total < 25_000_000, total
    # reference naming so .params files line up across frameworks
    for expect in ("conv_conv2d_weight",
                   "mixed_tower_1_conv_2_conv2d_weight",
                   "mixed_4_tower_1_conv_4_conv2d_weight",
                   "mixed_10_tower_mixed_conv_1_conv2d_weight",
                   "fc1_weight"):
        assert expect in names, expect


def test_inception_v3_small_classes_shapes():
    net = inception_v3.get_symbol(num_classes=7)
    _, out_shapes, _ = net.infer_shape(data=(1, 3, 299, 299))
    assert out_shapes == [(1, 7)]


@pytest.mark.slow
def test_inception_v3_forward():
    """One real forward pass executes and yields a normalized softmax."""
    net = inception_v3.get_symbol(num_classes=10)
    exe = net.simple_bind(ctx=mx.cpu(), data=(1, 3, 299, 299),
                          grad_req="null")
    rs = np.random.RandomState(0)
    for name, arr in exe.arg_dict.items():
        if name != "data":
            arr[:] = rs.uniform(-0.05, 0.05, arr.shape).astype(np.float32)
    for name, arr in exe.aux_dict.items():     # identity BN statistics
        arr[:] = 1.0 if name.endswith("moving_var") else 0.0
    exe.arg_dict["data"][:] = rs.rand(1, 3, 299, 299).astype(np.float32)
    out = exe.forward(is_train=False)[0].asnumpy()
    assert out.shape == (1, 10)
    np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-4)
