"""Chaos gate (@slow): kill a dist worker mid-epoch; survivors recover.

The acceptance criterion of ISSUE 9: survivors must save, re-form the
mesh/kvstore over the remaining workers, and resume from the last
committed checkpoint — no hang, loss-curve continuity, final accuracy
within tolerance of an uninterrupted run. Workers are spawned directly
(the launcher would tear the job down on the planned death) and re-exec
themselves through ``checkpoint.reexec_survivor`` on detection, the
supported re-mesh path (docs/checkpoint.md "Recovery flow").
"""
import os
import re
import socket
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(ROOT, "tools")


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn(n, tmp_path, kill_id=None, kill_at="1:3", epochs=4,
           timeout=420):
    port = _free_port()
    procs = []
    for sid in range(n):
        env = dict(os.environ)
        env.pop("MXNET_RECOVERY_GENERATION", None)
        env.update({
            "DMLC_ROLE": "worker", "DMLC_NUM_WORKER": str(n),
            "DMLC_WORKER_ID": str(sid),
            "DMLC_PS_ROOT_URI": "127.0.0.1",
            "DMLC_PS_ROOT_PORT": str(port),
            "PS_HEARTBEAT_TIMEOUT": "3",
            "MXNET_KVSTORE_RECOVERABLE": "1",
            "MXNET_CKPT_DEAD_PATIENCE": "15",
            # backstop: a survivor wedged inside a hung collective
            # re-execs after the grace instead of blocking forever
            "MXNET_CKPT_HANG_ACTION": "reexec",
            "MXNET_CKPT_HANG_GRACE": "20",
            # survivors idle past the heartbeat horizon at the kill
            # point so detection normally lands at a clean boundary
            "CHAOS_PAUSE_S": "6",
            "CHAOS_STABLE_ID": str(sid),
            "CHAOS_EPOCHS": str(epochs),
            "MXNET_CKPT_DIR": str(tmp_path / f"ck{sid}"),
            # every worker feeds the fleet-forensics plane: per-rank
            # jsonl dumps + a final registry snapshot
            "CHAOS_TELEMETRY_DIR": str(tmp_path / "fleet"),
        })
        if kill_id is not None:
            env["CHAOS_KILL_STABLE_ID"] = str(kill_id)
            env["CHAOS_KILL_AT"] = kill_at
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(ROOT, "tests",
                                          "chaos_worker.py")],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env, cwd=ROOT))
    outs, errs = [], []
    try:
        for p in procs:
            out, err = p.communicate(timeout=timeout)
            outs.append(out)
            errs.append(err)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        tails = []
        for p in procs:
            try:
                out, err = p.communicate(timeout=10)
            except Exception:
                out, err = "", ""
            tails.append(f"--- rc={p.returncode} stdout ---\n{out}\n"
                         f"--- stderr tail ---\n{err[-1200:]}")
        raise AssertionError(
            "chaos job wedged (the no-hang gate failed):\n"
            + "\n".join(tails))
    return procs, outs, errs


def _done_rows(outs):
    rows = {}
    for out in outs:
        for m in re.finditer(
                r"CHAOS_DONE stable=(\d+) rank=(\d+) gen=(\d+) "
                r"nworker=(\d+) acc=([\d.]+) params=([0-9a-f]+)",
                out):
            rows[int(m.group(1))] = {
                "rank": int(m.group(2)), "gen": int(m.group(3)),
                "nworker": int(m.group(4)), "acc": float(m.group(5)),
                "params": m.group(6)}
    return rows


def test_chaos_kill_one_worker_survivors_recover(tmp_path):
    """Kill stable-id 2 (the last rank — never the coordinator) at
    epoch 1, batch 3. Both survivors must detect, re-exec into a
    2-worker job at generation 1, resume from their last committed
    checkpoint, finish all epochs in lockstep, and land within
    tolerance of an uninterrupted 3-worker reference run."""
    procs, outs, errs = _spawn(3, tmp_path, kill_id=2)
    all_out = "\n".join(outs)

    # the doomed worker died the planned death
    assert procs[2].returncode == 17, (outs[2][-800:], errs[2][-800:])
    assert "CHAOS_KILL stable=2" in outs[2]

    # both survivors saw the death (flag or failed collective) and
    # re-formed instead of hanging
    assert all_out.count("CHAOS_DEAD_SEEN") == 2, (
        all_out[-1500:], "\n".join(e[-800:] for e in errs))
    for sid in (0, 1):
        assert procs[sid].returncode == 0, (outs[sid][-800:],
                                            errs[sid][-800:])

    done = _done_rows(outs)
    assert set(done) == {0, 1}
    for sid, row in done.items():
        assert row["gen"] == 1, row          # finished post-re-form
        assert row["nworker"] == 2, row      # over the survivor mesh
        assert row["acc"] > 0.8, row         # it learned
    # dist_sync lockstep held through the resume: identical params
    assert done[0]["params"] == done[1]["params"], done

    # ---- merged fleet report over the per-rank dumps ----------------
    # the dead rank's frozen dump, the survivors' detection dumps and
    # their re-formed generation-1 dumps merge into one story
    sys.path.insert(0, TOOLS)
    import fleetstat
    fleet_dir = tmp_path / "fleet"
    dumps = sorted(str(p) for p in fleet_dir.glob("rank*.jsonl"))
    assert len(dumps) >= 5, dumps  # r0/r1 at gen 0+1, r2 frozen at gen 0
    ranks = [fleetstat.load_file(p) for p in dumps]
    doc = fleetstat.build(ranks, gap_seconds=10.0)

    # the dead rank's last dump wall-clock sits a detection + re-exec +
    # resumed-training gap behind the survivors' — a heartbeat gap
    assert "2" in doc["dead"]["stale_ranks"], doc["dead"]
    # survivors reported the death (dead_node events in their gen-0
    # detection dumps) and finished at the bumped generation
    assert "2" in doc["dead"]["reported_dead"], doc["dead"]
    assert doc["generations"] == {"0": 1, "1": 1, "2": 0}, \
        doc["generations"]
    # recovery happened cleanly: survivors' metrics agree post-resume,
    # so the correctness-divergence scan must stay quiet
    assert doc["divergence"] == [], doc["divergence"]
    # the report is deterministic: same inputs, byte-identical text
    doc2 = fleetstat.build([fleetstat.load_file(p) for p in dumps],
                           gap_seconds=10.0)
    assert fleetstat.render(doc) == fleetstat.render(doc2)

    # loss-curve continuity: final accuracy within tolerance of an
    # uninterrupted 3-worker run of the same task
    _, ref_outs, ref_errs = _spawn(3, tmp_path / "ref", kill_id=None)
    ref = _done_rows(ref_outs)
    assert set(ref) == {0, 1, 2}, (ref_outs, ref_errs)
    ref_acc = sum(r["acc"] for r in ref.values()) / len(ref)
    for sid, row in done.items():
        assert abs(row["acc"] - ref_acc) < 0.15, (row, ref_acc)

    # ---- fleet merge over a real multi-process dist run -------------
    # the reference run's per-rank registry snapshots (taken while the
    # kvstore was live) must merge losslessly: exact counter sums,
    # histogram counts preserved bucket-wise, ranks from the dist plane
    import json as _json
    from mxnet_tpu.telemetry import fleet
    ref_fleet = tmp_path / "ref" / "fleet"
    snaps = []
    for sid in (0, 1, 2):
        with open(ref_fleet / f"fleet{sid}.json") as f:
            snaps.append(_json.load(f))
    merged = fleet.merge(snaps)
    assert merged["ranks"] == [0, 1, 2], merged["ranks"]
    batches = [slot for slot in merged["counters"].values()
               if slot["name"] == "module.fit.batches"]
    assert batches, sorted(merged["counters"])
    slot = batches[0]
    assert slot["total"] == sum(slot["by_rank"].values())
    # 4 epochs x 8 batches per worker, nothing lost in the merge
    assert sorted(slot["by_rank"]) == ["0", "1", "2"]
    assert all(v == 32 for v in slot["by_rank"].values()), slot
    hists = [slot for slot in merged["histograms"].values()
             if slot["name"] == "module.fit.batch.seconds"]
    assert hists, sorted(merged["histograms"])
    h = hists[0]
    assert h["merged"]["count"] == \
        sum(r["count"] for r in h["by_rank"].values()) == 96, h["merged"]
