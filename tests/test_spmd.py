"""GSPMD training path: one jitted program over the named mesh.

ISSUE 7 tentpole: ``Module.bind/fit(spmd=True)`` / ``MXNET_SPMD`` lowers
the fused and K-step-scan steps onto the ``parallel/mesh.py`` mesh with
``NamedSharding``-annotated params/data, the gradient collectives
emitted by XLA from the sharding specs instead of the kvstore — these
tests pin (a) the previously-untested substrate (MeshConfig/build_mesh
axis layout, placement.build_plan output-dim rules), (b) spmd-vs-
kvstore fit parity at K=1 and K=4 on the 8-virtual-device mesh,
(c) ZeRO-1-as-spec parity with the kvstore-era ZeroPlan (bit-for-bit
state shapes, N-fold cut preserved), (d) the kvstore-optional contract
and env-var plumbing, (e) the SH6xx mesh-aware lint rules, and (f) the
kernel tier composing unchanged under the mesh.
"""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu.parallel import MeshConfig, build_mesh, mesh_token, SpmdPlan
from mxnet_tpu import analysis

pytestmark = pytest.mark.skipif(
    len(jax.devices("cpu")) < 8, reason="needs 8 virtual cpu devices")

BATCH = 8
N_BATCHES = 8
CLASSES = 3
FEATS = 6


def _mlp(dropout=0.0, tagged=False):
    data = mx.sym.var("data")
    if tagged:
        with mx.AttrScope(ctx_group="stage0"):
            fc = mx.sym.FullyConnected(data=data, num_hidden=16,
                                       name="fc1")
    else:
        fc = mx.sym.FullyConnected(data=data, num_hidden=16, name="fc1")
    act = mx.sym.Activation(fc, act_type="relu")
    if dropout:
        act = mx.sym.Dropout(act, p=dropout)
    fc2 = mx.sym.FullyConnected(act, num_hidden=CLASSES, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def _data():
    rs = np.random.RandomState(0)
    X = rs.rand(N_BATCHES * BATCH, FEATS).astype(np.float32)
    y = rs.randint(0, CLASSES, (N_BATCHES * BATCH,)).astype(np.float32)
    return X, y


def _init_args():
    rs = np.random.RandomState(1)
    return {
        "fc1_weight": mx.nd.array(rs.randn(16, FEATS).astype(np.float32)
                                  * 0.1),
        "fc1_bias": mx.nd.array(np.zeros(16, np.float32)),
        "fc2_weight": mx.nd.array(rs.randn(CLASSES, 16).astype(np.float32)
                                  * 0.1),
        "fc2_bias": mx.nd.array(np.zeros(CLASSES, np.float32)),
    }


def _fit(spmd, kvstore="local", zero_stage=0, K=1, mesh=None, dropout=0.0,
         tagged=False, num_epoch=2, n_dev=8):
    """One fit; returns (params, per-batch metric trajectory, module)."""
    X, y = _data()
    mx.random.seed(7)
    it = mx.io.NDArrayIter(X, y, batch_size=BATCH)
    mod = mx.mod.Module(_mlp(dropout, tagged),
                        context=[mx.cpu(i) for i in range(n_dev)])
    accs = []

    def cb(param):
        accs.append(param.eval_metric.get()[1])

    mod.fit(it, num_epoch=num_epoch, spmd=spmd, mesh=mesh,
            zero_stage=zero_stage, steps_per_dispatch=K, kvstore=kvstore,
            batch_end_callback=cb,
            arg_params={k: v.copy() for k, v in _init_args().items()},
            optimizer_params=(("learning_rate", 0.1), ("momentum", 0.9)))
    args, _ = mod.get_params()
    return {k: v.asnumpy() for k, v in args.items()}, accs, mod


# ===================================================== substrate: mesh
def test_mesh_config_axis_layout():
    """MeshConfig drops size-1 axes; build_mesh orders axes so the
    chatty (model/seq) axes are innermost — adjacent devices."""
    cfg = MeshConfig(data=4, model=2, seq=1)
    assert cfg.sizes() == {"data": 4, "model": 2}
    mesh = build_mesh(cfg)
    assert mesh.axis_names == ("data", "model")
    assert mesh.shape["data"] == 4 and mesh.shape["model"] == 2
    # model innermost: one data row holds adjacent device ids
    ids = np.array([[d.id for d in row] for row in mesh.devices])
    assert ids.shape == (4, 2)
    assert (ids[:, 1] - ids[:, 0] == 1).all()

    # full 5-axis ordering: pipe/data outer, expert/seq/model inner
    mesh5 = build_mesh(MeshConfig(data=2, model=2, seq=2))
    assert mesh5.axis_names == ("data", "seq", "model")

    # defaulting: no sizes -> 1-D data axis over every device
    mesh1 = build_mesh()
    assert mesh1.axis_names == ("data",)
    assert mesh1.shape["data"] == len(jax.devices())

    with pytest.raises(ValueError):
        build_mesh(MeshConfig(data=1024))


def test_mesh_config_from_env(monkeypatch):
    """MXNET_MESH_* env overrides build the config; the data axis
    defaults to the leftover device count."""
    monkeypatch.setenv("MXNET_MESH_MODEL", "2")
    cfg = MeshConfig.from_env(8)
    assert cfg.model == 2 and cfg.data == 4
    monkeypatch.setenv("MXNET_MESH_DATA", "2")
    cfg = MeshConfig.from_env(8)
    assert cfg.data == 2 and cfg.model == 2
    monkeypatch.delenv("MXNET_MESH_MODEL")
    monkeypatch.delenv("MXNET_MESH_DATA")
    assert MeshConfig.from_env(8) is None
    monkeypatch.setenv("MXNET_MESH_DATA", "nope")
    with pytest.raises(ValueError):
        MeshConfig.from_env(8)


def test_mesh_token_distinguishes_topologies():
    devs = jax.devices("cpu")
    t1 = mesh_token(build_mesh(MeshConfig(data=8), devices=devs))
    t2 = mesh_token(build_mesh(MeshConfig(data=4, model=2), devices=devs))
    t3 = mesh_token(build_mesh(MeshConfig(data=4), devices=devs))
    assert len({t1, t2, t3}) == 3
    # same topology -> same token
    assert t1 == mesh_token(build_mesh(MeshConfig(data=8), devices=devs))


# ================================================ substrate: placement
def test_build_plan_output_dim_rules():
    """placement.build_plan shards matmul-like weights on their OUTPUT
    dim (never a contraction dim) and replicates what it cannot prove;
    biases of sharded layers shard elementwise."""
    from jax.sharding import PartitionSpec as P
    from mxnet_tpu.parallel.placement import build_plan

    data = mx.sym.var("data")
    with mx.AttrScope(ctx_group="g0"):
        fc = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
        act = mx.sym.Activation(fc, act_type="relu")
    with mx.AttrScope(ctx_group="g1"):
        fc2 = mx.sym.FullyConnected(act, num_hidden=3, name="fc2")
    sym = mx.sym.SoftmaxOutput(fc2, name="softmax")

    shapes = {"fc1_weight": (16, FEATS), "fc1_bias": (16,),
              "fc2_weight": (3, 16), "fc2_bias": (3,)}
    plan = build_plan(sym, {"g0": mx.cpu(0), "g1": mx.cpu(1)}, shapes)
    assert plan is not None
    # FC weight (num_hidden, in_dim): output dim is 0 — sharding dim 1
    # would put the contraction on the wire every apply
    assert plan.param_shardings["fc1_weight"].spec == P("model", None)
    assert plan.param_shardings["fc1_bias"].spec == P("model")
    # 3 not divisible by 2 -> replicated, never mis-sharded
    assert plan.param_shardings["fc2_weight"].spec == P()
    # no group2ctx / no tags -> no plan at all
    assert build_plan(sym, {}, shapes) is None
    assert build_plan(_mlp(), {"g0": mx.cpu(0), "g1": mx.cpu(1)},
                      shapes) is None


def test_spmd_plan_records_replication_reasons():
    """A tagged-but-unshardable param is recorded with its reason (the
    SH602 surface)."""
    sym = _mlp(tagged=True)
    plan = SpmdPlan.build(
        sym, jax.devices("cpu")[:8],
        {"fc1_weight": (16, FEATS), "fc1_bias": (16,),
         "fc2_weight": (3, 16), "fc2_bias": (3,)},
        config=MeshConfig(data=2, model=4))
    from jax.sharding import PartitionSpec as P
    assert plan.param_spec("fc1_weight") == P("model", None)
    assert plan.param_spec("fc2_weight") == P()          # untagged
    assert "fc1_bias" in plan.param_specs
    assert plan.unsharded_tagged == {}                   # 16 % 4 == 0
    plan5 = SpmdPlan.build(
        sym, jax.devices("cpu")[:8],
        {"fc1_weight": (15, FEATS), "fc1_bias": (15,)},
        config=MeshConfig(data=2, model=4))
    assert "fc1_weight" in plan5.unsharded_tagged
    assert "divisible" in plan5.unsharded_tagged["fc1_weight"]


# ============================================== spmd-vs-kvstore parity
@pytest.mark.parametrize("K", [1, 4])
def test_spmd_fit_matches_kvstore_overlap(K):
    """fit(spmd=True) must reproduce the kvstore-overlap arrangement —
    per-batch loss/metric trajectory and final params — at K=1 and
    under the K=4 scan (acceptance criterion)."""
    p_kv, a_kv, mod_kv = _fit(False, kvstore="dist_sync", K=1)
    assert mod_kv._kvstore is not None          # the kvstore path ran
    p_sp, a_sp, mod_sp = _fit(True, K=K)
    assert mod_sp._kvstore is None
    assert mod_sp._fused_armed
    if K > 1:
        assert mod_sp._exec_group._scan_K == K
    for k in p_kv:
        np.testing.assert_allclose(p_kv[k], p_sp[k], rtol=2e-5, atol=2e-6,
                                   err_msg=k)
    np.testing.assert_allclose(a_kv, a_sp, rtol=1e-6)


def test_spmd_matches_update_on_kvstore_store():
    """Parity against the device-store post-hoc push/pull arrangement
    (the store's updater owns the math there)."""
    p_kv, a_kv, mod_kv = _fit(False, kvstore="device")
    assert mod_kv._update_on_kvstore
    p_sp, a_sp, _ = _fit(True)
    for k in p_kv:
        np.testing.assert_allclose(p_kv[k], p_sp[k], rtol=2e-5, atol=2e-6,
                                   err_msg=k)
    np.testing.assert_allclose(a_kv, a_sp, rtol=1e-6)


def test_spmd_kvstore_dropped_and_optional():
    """In spmd mode a local/device kvstore is dropped (in-program
    collectives own the reduction); kvstore=None works outright."""
    _, _, mod = _fit(True, kvstore="device")
    assert mod._kvstore is None and not mod._update_on_kvstore
    _, _, mod2 = _fit(True, kvstore=None)
    assert mod2._kvstore is None and mod2._fused_armed


def test_spmd_env_var(monkeypatch):
    """MXNET_SPMD=1 selects the spmd binding without the kwarg."""
    monkeypatch.setenv("MXNET_SPMD", "1")
    _, _, mod = _fit(None)
    assert mod._exec_group._spmd_plan is not None
    monkeypatch.setenv("MXNET_SPMD", "0")
    _, _, mod = _fit(None)
    assert mod._exec_group._spmd_plan is None


def test_spmd_model_axis_parity():
    """data=4 x model=2 with ctx_group-tagged params: fc1 shards on the
    model axis, numerics match pure data-parallel."""
    p0, a0, _ = _fit(False)
    p1, a1, mod = _fit(True, tagged=True, mesh=MeshConfig(data=4, model=2))
    plan = mod._exec_group._spmd_plan
    from jax.sharding import PartitionSpec as P
    assert plan.param_spec("fc1_weight") == P("model", None)
    exe = mod._exec_group.executor
    sh = exe.arg_dict["fc1_weight"].asjax().sharding
    assert sh.is_equivalent_to(plan.param_sharding("fc1_weight"), 2)
    # each model-shard holds half the rows
    shards = exe.arg_dict["fc1_weight"].asjax().addressable_shards
    assert {s.data.shape for s in shards} == {(8, FEATS)}
    for k in p0:
        np.testing.assert_allclose(p0[k], p1[k], rtol=2e-5, atol=2e-6,
                                   err_msg=k)
    np.testing.assert_allclose(a0, a1, rtol=1e-6)


def test_spmd_dropout_scan_self_consistent():
    """K=4 scan == K=1 under spmd with dropout (shared device rng
    chain, same contract as the kvstore-era fused path)."""
    p1, a1, _ = _fit(True, dropout=0.3, K=1)
    p4, a4, mod = _fit(True, dropout=0.3, K=4)
    assert mod._exec_group._scan_K == 4
    for k in p1:
        np.testing.assert_allclose(p1[k], p4[k], rtol=2e-5, atol=2e-6,
                                   err_msg=k)
    np.testing.assert_allclose(a1, a4, rtol=1e-12)


# ======================================================= ZeRO-1 as spec
@pytest.mark.parametrize("optimizer", ["sgd", "adam"])
def test_spmd_zero1_as_spec_matches_zeroplan(optimizer):
    """ZeRO-1 under spmd is a PartitionSpec change on the state leaves;
    it must match the kvstore-era ZeroPlan arrangement bit-for-bit in
    state SHAPES (same (n, chunk) flat layout, N-fold cut) and to float
    ulps in values."""
    X, y = _data()

    def fit(spmd):
        mx.random.seed(7)
        it = mx.io.NDArrayIter(X, y, batch_size=BATCH)
        mod = mx.mod.Module(_mlp(),
                            context=[mx.cpu(i) for i in range(8)])
        opt_params = (("learning_rate", 0.1), ("momentum", 0.9)) \
            if optimizer == "sgd" else (("learning_rate", 0.01),)
        mod.fit(it, num_epoch=1, spmd=spmd, zero_stage=1,
                kvstore=None if spmd else "local", optimizer=optimizer,
                arg_params={k: v.copy() for k, v in _init_args().items()},
                optimizer_params=opt_params)
        args, _ = mod.get_params()
        return ({k: v.asnumpy() for k, v in args.items()}, mod)

    p_zp, mod_zp = fit(False)
    p_sp, mod_sp = fit(True)
    assert mod_zp._exec_group._zero_plan is not None     # ZeroPlan path
    assert mod_sp._exec_group._zero_plan is None         # spec path
    assert mod_sp._exec_group._spmd_plan.zero

    st_zp = mod_zp._exec_group._fused_states
    st_sp = mod_sp._exec_group._fused_states
    for nm in st_zp:
        for l_zp, l_sp in zip(jax.tree.leaves(st_zp[nm]),
                              jax.tree.leaves(st_sp[nm])):
            assert l_zp.shape == l_sp.shape == (8, l_zp.shape[1])
            # N-fold cut: one 1/N slice per device on both paths
            assert len(l_sp.addressable_shards) == 8
            assert all(s.data.shape[0] == 1
                       for s in l_sp.addressable_shards)
            np.testing.assert_allclose(np.asarray(l_zp),
                                       np.asarray(l_sp),
                                       rtol=1e-6, atol=1e-7, err_msg=nm)
    for k in p_zp:
        np.testing.assert_allclose(p_zp[k], p_sp[k], rtol=1e-6,
                                   atol=1e-6, err_msg=k)


def test_spmd_zero1_checkpoint_roundtrip(tmp_path):
    """spmd ZeRO states save/load across arrangements (same param-shaped
    checkpoint representation as ZeroPlan)."""
    fname = str(tmp_path / "spmd_zero.states")
    _, _, mod_sp = _fit(True, zero_stage=1, num_epoch=1)
    assert mod_sp._exec_group._state_layout is not None
    mod_sp.save_optimizer_states(fname)
    _, _, mod_zp = _fit(False, zero_stage=1, num_epoch=1)
    mod_zp.load_optimizer_states(fname)
    s_sp = mod_sp._exec_group.export_fused_states()
    s_zp = mod_zp._exec_group.export_fused_states()
    for nm in s_sp:
        for a, b in zip(jax.tree.leaves(s_sp[nm]),
                        jax.tree.leaves(s_zp[nm])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=nm)


def test_spmd_zero1_with_model_axis():
    """ZeRO flat-shard update composes with model-sharded params on a
    2-D mesh (the pad-vs-concatenate partitioner hazard regression)."""
    p0, a0, _ = _fit(False)
    p1, a1, mod = _fit(True, tagged=True, zero_stage=1,
                       mesh=MeshConfig(data=4, model=2))
    assert mod._exec_group._spmd_plan.zero
    for k in p0:
        np.testing.assert_allclose(p0[k], p1[k], rtol=2e-5, atol=2e-6,
                                   err_msg=k)
    np.testing.assert_allclose(a0, a1, rtol=1e-6)


# ======================================================== SH6xx linting
def test_spmd_lint_clean():
    """A healthy spmd module binds with ZERO SH findings (the
    conftest-wide validate=warn gate must stay clean)."""
    _, _, mod = _fit(True, zero_stage=1)
    rep = analysis.lint_module(mod)
    assert [d for d in rep if d.rule.startswith("SH")] == []


def test_sh601_sh603_sharding_mismatch():
    """A param re-bound with the wrong sharding trips SH601 (binding
    contract) and SH603 (donated carry cannot alias)."""
    _, _, mod = _fit(True)
    exe = mod._exec_group.executor
    exe.arg_dict["fc1_weight"]._set(
        jax.device_put(exe.arg_dict["fc1_weight"].asjax(),
                       mod._exec_group._data_sharding))
    rules = sorted(d.rule for d in analysis.lint_module(mod)
                   if d.rule.startswith("SH"))
    assert rules == ["SH601", "SH603"]


def test_sh602_accidental_replication():
    """A ctx_group-tagged param that cannot shard on the model axis
    (indivisible dim) is flagged as accidentally replicated."""
    _, _, mod = _fit(True, tagged=True, mesh=MeshConfig(data=2, model=4))
    plan = mod._exec_group._spmd_plan
    assert plan.unsharded_tagged == {}          # 16 % 4 == 0: clean
    X, y = _data()
    it = mx.io.NDArrayIter(X, y, batch_size=BATCH)
    data = mx.sym.var("data")
    with mx.AttrScope(ctx_group="stage0"):
        fc = mx.sym.FullyConnected(data, num_hidden=15, name="fc1")
    act = mx.sym.Activation(fc, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=CLASSES, name="fc2")
    sym = mx.sym.SoftmaxOutput(fc2, name="softmax")
    mod2 = mx.mod.Module(sym, context=[mx.cpu(i) for i in range(8)])
    mod2.bind([("data", (BATCH, FEATS))], [("softmax_label", (BATCH,))],
              spmd=True, mesh=MeshConfig(data=2, model=4))
    mod2.init_params(mx.initializer.Xavier())
    rep = analysis.lint_module(mod2)
    sh602 = [d for d in rep if d.rule == "SH602"]
    assert sh602 and any(d.node == "fc1_weight" for d in sh602)
    assert all(d.rule != "SH601" for d in rep)


def test_sh603_state_leaf_mismatch():
    """An optimizer-state leaf imported with the wrong sharding trips
    the donated-carry rule."""
    _, _, mod = _fit(True, zero_stage=1)
    g = mod._exec_group
    nm = g._fused_watched[0]
    g._fused_states[nm] = jax.tree.map(
        lambda x: jax.device_put(np.asarray(x), g._repl_sharding),
        g._fused_states[nm])
    rules = [d.rule for d in analysis.lint_module(mod)]
    assert "SH603" in rules


# =============================================== kernel tier composition
def test_kernel_tier_composes_under_mesh(monkeypatch):
    """MXNET_KERNEL_TIER=xla under spmd is bit-identical to the default
    (auto resolves to xla on CPU): tier dispatch happens inside the
    traced runner and is sharding-agnostic."""
    monkeypatch.setenv("MXNET_KERNEL_TIER", "xla")
    p_xla, a_xla, _ = _fit(True)
    monkeypatch.delenv("MXNET_KERNEL_TIER")
    p_auto, a_auto, _ = _fit(True)
    for k in p_xla:
        np.testing.assert_array_equal(p_xla[k], p_auto[k], err_msg=k)
    np.testing.assert_array_equal(a_xla, a_auto)


# ===================================================== score/eval path
def test_spmd_score_and_predict():
    """Eval forward runs over the same sharded binding (score consumes
    the train module directly)."""
    X, y = _data()
    _, _, mod = _fit(True, zero_stage=1)
    it = mx.io.NDArrayIter(X, y, batch_size=BATCH)
    res = dict(mod.score(it, "acc"))
    assert 0.0 <= res["accuracy"] <= 1.0
