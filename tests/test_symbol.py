"""Symbol tests (mirrors reference tests/python/unittest/test_symbol.py)."""
import json
import os
import tempfile

import numpy as np

import mxnet_tpu as mx


def _mlp():
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data=data, num_hidden=128, name="fc1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(data=net, num_hidden=10, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def test_symbol_compose():
    data = mx.sym.var("data")
    net1 = mx.sym.FullyConnected(data=data, name="fc1", num_hidden=10)
    net1 = mx.sym.FullyConnected(data=net1, name="fc2", num_hidden=100)
    assert net1.list_arguments() == ["data", "fc1_weight", "fc1_bias",
                                     "fc2_weight", "fc2_bias"]
    net2 = mx.sym.FullyConnected(data=mx.sym.var("data2"), name="fc3",
                                 num_hidden=10)
    net2 = mx.sym.Activation(net2, act_type="relu")
    net2 = mx.sym.FullyConnected(data=net2, name="fc4", num_hidden=20)
    composed = net2(data2=net1, name="composed")
    multi_out = mx.sym.Group([composed, net1])
    assert len(multi_out) == 2


def test_symbol_internals():
    data = mx.sym.var("data")
    oldfc = mx.sym.FullyConnected(data=data, name="fc1", num_hidden=10)
    net1 = mx.sym.FullyConnected(data=oldfc, name="fc2", num_hidden=100)
    internals = net1.get_internals()
    fc1 = internals["fc1_output"]
    assert fc1.list_arguments() == oldfc.list_arguments()


def test_symbol_outputs():
    net = _mlp()
    assert net.list_outputs() == ["softmax_output"]
    assert "data" in net.list_arguments()
    assert net.name == "softmax"


def test_symbol_infer_shape():
    net = _mlp()
    arg_shapes, out_shapes, aux_shapes = net.infer_shape(data=(32, 100))
    args = dict(zip(net.list_arguments(), arg_shapes))
    assert args["fc1_weight"] == (128, 100)
    assert args["fc1_bias"] == (128,)
    assert args["fc2_weight"] == (10, 128)
    assert out_shapes == [(32, 10)]


def test_symbol_infer_shape_partial():
    data = mx.sym.var("data")
    prev = mx.sym.var("prev")
    fc1 = mx.sym.FullyConnected(data=data, name="fc1", num_hidden=64)
    fc2 = mx.sym.FullyConnected(data=prev, name="fc2", num_hidden=64)
    out = fc1 + fc2
    arg_shapes, out_shapes, _ = out.infer_shape_partial(data=(32, 100))
    args = dict(zip(out.list_arguments(), arg_shapes))
    assert args["fc1_weight"] == (64, 100)
    # fc2 side unknown without prev shape
    assert args["fc2_weight"] is None or args["fc2_weight"] == (64, 100)


def test_infer_shape_mismatch_carries_provenance():
    """A shape conflict names the failing op, node, input names, and the
    shapes inferred so far — not just 'incompatible shapes (a) vs (b)'."""
    import pytest
    data = mx.sym.var("data")
    fc1 = mx.sym.FullyConnected(data, name="fc1", num_hidden=8)
    out = fc1 + mx.sym.var("skip")
    with pytest.raises(mx.MXNetError) as err:
        out.infer_shape(data=(4, 6), skip=(4, 9))
    msg = str(err.value)
    assert "_plus" in msg                      # op name
    assert "fc1" in msg and "skip" in msg      # input provenance
    assert "(4, 8)" in msg and "(4, 9)" in msg  # inferred-so-far shapes


def test_infer_shape_bad_weight_names_node():
    """Explicitly mis-shaped weights fail with the node's provenance."""
    import pytest
    data = mx.sym.var("data")
    fc1 = mx.sym.FullyConnected(data, name="fc1", num_hidden=8)
    with pytest.raises(mx.MXNetError) as err:
        fc1.infer_shape(data=(4, 6), fc1_weight=(8, 999))
    msg = str(err.value)
    assert "FullyConnected" in msg and "fc1" in msg


def test_symbol_json_roundtrip():
    net = _mlp()
    js = net.tojson()
    data = json.loads(js)
    assert "nodes" in data and "heads" in data
    net2 = mx.sym.load_json(js)
    assert net2.list_arguments() == net.list_arguments()
    assert net2.list_outputs() == net.list_outputs()
    # shapes still infer identically
    s1 = net.infer_shape(data=(8, 50))
    s2 = net2.infer_shape(data=(8, 50))
    assert s1 == s2
    with tempfile.TemporaryDirectory() as d:
        fname = os.path.join(d, "sym.json")
        net.save(fname)
        net3 = mx.sym.load(fname)
        assert net3.list_arguments() == net.list_arguments()


def test_symbol_arithmetic():
    a = mx.sym.var("a")
    b = mx.sym.var("b")
    c = a + b * 2 - 1
    ex = c.bind(mx.cpu(), args={"a": mx.nd.ones((2, 2)),
                                "b": mx.nd.ones((2, 2)) * 3})
    out = ex.forward()
    np.testing.assert_allclose(out[0].asnumpy(), np.full((2, 2), 6.0))


def test_symbol_attr():
    data = mx.sym.var("data", attr={"mood": "angry"})
    op = mx.sym.Convolution(data=data, name="conv", kernel=(1, 1),
                            num_filter=1, attr={"__mood__": "so so"})
    assert data.attr("mood") == "angry"
    assert op.attr("__mood__") == "so so"


def test_symbol_grouped():
    a = mx.sym.var("a")
    b = mx.sym.var("b")
    g = mx.sym.Group([a + b, a * b])
    assert len(g.list_outputs()) == 2
    outs = g.bind(mx.cpu(), args={"a": mx.nd.ones((2,)) * 2,
                                  "b": mx.nd.ones((2,)) * 3}).forward()
    np.testing.assert_allclose(outs[0].asnumpy(), [5, 5])
    np.testing.assert_allclose(outs[1].asnumpy(), [6, 6])


def test_symbol_zeros_ones():
    z = mx.sym.zeros((2, 3)) + mx.sym.ones((2, 3))
    out = z.bind(mx.cpu(), args={}).forward()
    np.testing.assert_allclose(out[0].asnumpy(), np.ones((2, 3)))


def test_load_legacy_json_key_spellings():
    """Pre-NNVM checkpoints spell node attributes "param"/"attr"
    (reference: legacy_json_util.cc UpgradeJSON); loading must accept
    them and produce the same graph as the modern format."""
    import json
    legacy = json.dumps({
        "nodes": [
            {"op": "null", "name": "data", "inputs": []},
            {"op": "null", "name": "fc_weight", "inputs": []},
            {"op": "null", "name": "fc_bias", "inputs": []},
            {"op": "FullyConnected", "name": "fc",
             "param": {"num_hidden": "4"},
             "inputs": [[0, 0, 0], [1, 0, 0], [2, 0, 0]]},
            {"op": "Activation", "name": "act",
             "attr": {"act_type": "relu"},
             "inputs": [[3, 0, 0]]},
        ],
        "arg_nodes": [0, 1, 2],
        "heads": [[4, 0, 0]],
    })
    sym = mx.sym.load_json(legacy)
    assert sym.list_arguments() == ["data", "fc_weight", "fc_bias"]
    d = np.random.RandomState(0).rand(2, 3).astype("f")
    w = np.random.RandomState(1).rand(4, 3).astype("f") - 0.5
    b = np.zeros(4, "f")
    exe = sym.bind(mx.cpu(), args={"data": mx.nd.array(d),
                                   "fc_weight": mx.nd.array(w),
                                   "fc_bias": mx.nd.array(b)},
                   grad_req="null")
    exe.forward(is_train=False)
    np.testing.assert_allclose(exe.outputs[0].asnumpy(),
                               np.maximum(d @ w.T, 0), rtol=1e-5)


def test_load_legacy_json_merges_param_and_attr():
    """A legacy node can carry op params in "param" AND user attrs in
    "attr" simultaneously — both must survive the upgrade."""
    import json
    legacy = json.dumps({
        "nodes": [
            {"op": "null", "name": "data", "inputs": []},
            {"op": "null", "name": "fc_weight", "inputs": []},
            {"op": "null", "name": "fc_bias", "inputs": []},
            {"op": "FullyConnected", "name": "fc",
             "param": {"num_hidden": "4"},
             "attr": {"__lr_mult__": "0.1"},
             "inputs": [[0, 0, 0], [1, 0, 0], [2, 0, 0]]},
        ],
        "arg_nodes": [0, 1, 2],
        "heads": [[3, 0, 0]],
    })
    sym = mx.sym.load_json(legacy)
    _, out_shapes, _ = sym.infer_shape(data=(2, 3))
    assert tuple(out_shapes[0]) == (2, 4)   # num_hidden survived


def test_json_roundtrip_preserves_ctx_group():
    """ctx_group placement tags on op nodes must survive tojson/load_json
    in _extra (placement.py reads them there), not leak into op attrs."""
    with mx.AttrScope(ctx_group="g0"):
        d = mx.sym.var("data")
        fc = mx.sym.FullyConnected(d, num_hidden=4, name="fc")
    loaded = mx.sym.load_json(fc.tojson())
    node = [n for n in loaded._topo_nodes() if not n.is_variable][0]
    assert node._extra.get("ctx_group") == "g0"
    assert "ctx_group" not in node.attrs
