"""IO tests (mirrors reference tests/python/unittest/test_io.py)."""
import os
import tempfile

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal


def test_ndarray_iter_basic():
    data = np.arange(100).reshape(25, 4).astype(np.float32)
    labels = np.arange(25).astype(np.float32)
    it = mx.io.NDArrayIter(data, labels, batch_size=5)
    batches = list(it)
    assert len(batches) == 5
    assert batches[0].data[0].shape == (5, 4)
    assert batches[0].label[0].shape == (5,)
    assert_almost_equal(batches[0].data[0], data[:5])
    it.reset()
    assert len(list(it)) == 5


def test_ndarray_iter_pad():
    data = np.arange(22 * 3).reshape(22, 3).astype(np.float32)
    it = mx.io.NDArrayIter(data, np.zeros(22, dtype=np.float32),
                           batch_size=5, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 5
    assert batches[-1].pad == 3
    it2 = mx.io.NDArrayIter(data, np.zeros(22, dtype=np.float32),
                            batch_size=5, last_batch_handle="discard")
    assert len(list(it2)) == 4


def test_ndarray_iter_dict_data():
    it = mx.io.NDArrayIter({"a": np.ones((10, 2), dtype=np.float32),
                            "b": np.zeros((10, 3), dtype=np.float32)},
                           batch_size=5)
    names = sorted(d.name for d in it.provide_data)
    assert names == ["a", "b"]


def test_resize_iter():
    data = np.zeros((12, 2), dtype=np.float32)
    base = mx.io.NDArrayIter(data, np.zeros(12, dtype=np.float32),
                             batch_size=4)
    r = mx.io.ResizeIter(base, 10)
    assert len(list(r)) == 10


def test_prefetching_iter():
    data = np.random.rand(20, 3).astype(np.float32)
    base = mx.io.NDArrayIter(data, np.zeros(20, dtype=np.float32),
                             batch_size=5)
    pf = mx.io.PrefetchingIter(base)
    batches = list(pf)
    assert len(batches) == 4
    pf.reset()
    batches2 = list(pf)
    assert len(batches2) == 4
    assert_almost_equal(batches[0].data[0], batches2[0].data[0])


def test_csv_iter():
    with tempfile.TemporaryDirectory() as d:
        data_path = os.path.join(d, "data.csv")
        label_path = os.path.join(d, "label.csv")
        data = np.random.rand(30, 4).astype(np.float32)
        labels = np.arange(30).astype(np.float32)
        np.savetxt(data_path, data, delimiter=",")
        np.savetxt(label_path, labels, delimiter=",")
        it = mx.io.CSVIter(data_csv=data_path, data_shape=(4,),
                           label_csv=label_path, batch_size=10)
        batches = list(it)
        assert len(batches) == 3
        assert_almost_equal(batches[0].data[0], data[:10], rtol=1e-5)


def test_mnist_iter():
    """Write a tiny idx-format file pair and read it back."""
    import struct
    with tempfile.TemporaryDirectory() as d:
        img_path = os.path.join(d, "images-idx3-ubyte")
        lab_path = os.path.join(d, "labels-idx1-ubyte")
        n = 20
        imgs = (np.random.rand(n, 28, 28) * 255).astype(np.uint8)
        labs = (np.arange(n) % 10).astype(np.uint8)
        with open(img_path, "wb") as f:
            f.write(struct.pack(">IIII", 2051, n, 28, 28))
            f.write(imgs.tobytes())
        with open(lab_path, "wb") as f:
            f.write(struct.pack(">II", 2049, n))
            f.write(labs.tobytes())
        it = mx.io.MNISTIter(image=img_path, label=lab_path, batch_size=5,
                             shuffle=False)
        batch = next(iter(it))
        assert batch.data[0].shape == (5, 1, 28, 28)
        assert batch.data[0].asnumpy().max() <= 1.0
        assert_almost_equal(batch.label[0],
                            labs[:5].astype(np.float32))
        flat_it = mx.io.MNISTIter(image=img_path, label=lab_path,
                                  batch_size=5, flat=True, shuffle=False)
        assert next(iter(flat_it)).data[0].shape == (5, 784)


def test_data_desc():
    d = mx.io.DataDesc("data", (32, 3, 224, 224))
    assert d.name == "data"
    assert d.shape == (32, 3, 224, 224)
    assert mx.io.DataDesc.get_batch_axis("NCHW") == 0
    assert mx.io.DataDesc.get_batch_axis("TNC") == 1
