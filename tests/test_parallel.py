"""Parallel-layer tests: mesh building, collectives, ring attention,
sharded data-parallel executor (runs on the 8-virtual-CPU-device mesh)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu.parallel import (build_mesh, data_sharding, replicated,
                                all_reduce, all_gather, reduce_scatter,
                                shard_map)
from mxnet_tpu.parallel.ring_attention import (attention, ring_attention,
                                               ring_attention_sharded)

pytestmark = pytest.mark.skipif(
    len(jax.devices("cpu")) < 8, reason="needs 8 virtual cpu devices")


def _cpu_devices():
    return jax.devices("cpu")


def test_build_mesh_axes():
    mesh = build_mesh(data=4, model=2, devices=_cpu_devices())
    assert mesh.shape["data"] == 4
    assert mesh.shape["model"] == 2
    mesh1 = build_mesh(devices=_cpu_devices())
    assert mesh1.shape["data"] == 8


def test_sharded_psum():
    mesh = build_mesh(data=8, devices=_cpu_devices())

    @shard_map(mesh=mesh, in_specs=P("data"), out_specs=P())
    def total(x):
        return all_reduce(jnp.sum(x), "data")

    x = jnp.arange(64, dtype=jnp.float32)
    out = total(jax.device_put(x, NamedSharding(mesh, P("data"))))
    assert float(out) == x.sum()


def test_all_gather_reduce_scatter():
    mesh = build_mesh(data=4, devices=_cpu_devices())

    @shard_map(mesh=mesh, in_specs=P("data"), out_specs=P("data"))
    def ag_rs(x):
        full = all_gather(x, "data")            # (16,)
        return reduce_scatter(full, "data")     # each gets sum-of-shards
    x = jnp.arange(16, dtype=jnp.float32)
    out = ag_rs(jax.device_put(x, NamedSharding(mesh, P("data"))))
    # all_gather tiles to full vector, psum_scatter sums the 4 copies of
    # each position group -> 4x the original shard values reassembled
    np.testing.assert_allclose(np.asarray(out), np.asarray(x) * 4)


def test_ring_attention_matches_full():
    mesh = build_mesh(seq=8, devices=_cpu_devices())
    rng = np.random.RandomState(0)
    B, H, T, D = 2, 3, 32, 8
    q = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    expect = attention(q, k, v)
    with mesh:
        got = ring_attention_sharded(q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_causal():
    mesh = build_mesh(seq=4, devices=_cpu_devices())
    rng = np.random.RandomState(1)
    B, H, T, D = 1, 2, 16, 4
    q = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    expect = attention(q, k, v, causal=True)
    with mesh:
        got = ring_attention_sharded(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_grads():
    """Ring attention must be differentiable (it sits in training graphs)."""
    mesh = build_mesh(seq=4, devices=_cpu_devices())
    rng = np.random.RandomState(2)
    B, H, T, D = 1, 1, 8, 4
    q = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))

    def loss_full(q, k, v):
        return jnp.sum(attention(q, k, v) ** 2)

    spec = P(None, None, "seq", None)

    @jax.jit
    def loss_ring(q, k, v):
        @shard_map(mesh=mesh, in_specs=(spec,) * 3, out_specs=spec)
        def att(qs, ks, vs):
            return ring_attention(qs, ks, vs, axis_name="seq")
        return jnp.sum(att(q, k, v) ** 2)

    g_full = jax.grad(loss_full)(q, k, v)
    with mesh:
        g_ring = jax.grad(loss_ring)(
            jax.device_put(q, NamedSharding(mesh, spec)),
            jax.device_put(k, NamedSharding(mesh, spec)),
            jax.device_put(v, NamedSharding(mesh, spec)))
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_full),
                               rtol=1e-3, atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_flash_grads(causal):
    """Flash-ring backward (custom_vjp recomputing through the XLA ring)
    must match full-attention gradients — locks in what was previously
    only hand-verified."""
    mesh = build_mesh(seq=4, devices=_cpu_devices()[:4])
    rng = np.random.RandomState(5)
    B, H, T, D = 1, 2, 32, 8
    q = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))

    def loss_full(q, k, v):
        return jnp.sum(attention(q, k, v, causal=causal) ** 2)

    def loss_ring(q, k, v):
        return jnp.sum(
            ring_attention_sharded(q, k, v, mesh, causal=causal,
                                   use_flash=True) ** 2)

    g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    with mesh:
        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for gr, gf in zip(g_ring, g_full):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf),
                                   rtol=1e-3, atol=1e-4)


def test_mesh_scope():
    from mxnet_tpu.parallel import current_mesh, mesh_scope
    mesh = build_mesh(data=2, devices=_cpu_devices())
    assert current_mesh() is None
    with mesh_scope(mesh):
        assert current_mesh() is mesh
    assert current_mesh() is None


def test_ring_attention_flash_block_matches_full():
    """Flash-kernel ring (Pallas local block, interpret mode on this CPU
    mesh via check_vma=False) must match full attention exactly like the
    XLA-block ring does."""
    mesh = build_mesh(seq=4, devices=_cpu_devices()[:4])
    rng = np.random.RandomState(3)
    B, H, T, D = 2, 2, 32, 8
    q = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    expect = attention(q, k, v)
    with mesh:
        got = ring_attention_sharded(q, k, v, mesh, use_flash=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_flash_block_causal():
    """Causal flash ring: static per-step offsets + wrapped-shard gating
    must reproduce the absolute-position mask exactly."""
    mesh = build_mesh(seq=4, devices=_cpu_devices()[:4])
    rng = np.random.RandomState(4)
    B, H, T, D = 1, 2, 16, 4
    q = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    expect = attention(q, k, v, causal=True)
    with mesh:
        got = ring_attention_sharded(q, k, v, mesh, causal=True,
                                     use_flash=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               rtol=2e-4, atol=2e-5)
