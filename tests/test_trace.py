"""Request-scoped trace plane + step-time attribution (ISSUE 14).

Gates, per the acceptance criteria:

* a served request — including a multi-step stateful decode session —
  reconstructs to a SINGLE parented span tree from the trace buffer /
  ring export, deterministic under FakeClock;
* ``step.phase.*`` histograms sum to within 5% of the measured step
  wall time on both the fused (K=1) and the K=4 scan paths;
* ``Histogram.quantile``'s exemplar plumbing leaves the default
  Prometheus exposition byte-identical (golden-output test), and
  trace records stay inside the flight ring's capacity bound.
"""
import json
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry as tm
from mxnet_tpu.serve import FakeClock
from mxnet_tpu.telemetry import stepattr as sa
from mxnet_tpu.telemetry import trace as trc


@pytest.fixture(autouse=True)
def _clean_trace_state():
    trc.configure(capacity=4096, sample=1.0, reset_ids=True)
    trc.clear()
    sa.reset()
    tm.flightrec.clear()
    yield
    sa.configure(armed=None)
    trc.configure(capacity=4096, sample=1.0)


def _mlp(prefix="fc", feat=6, hidden=8, classes=3):
    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data=data, num_hidden=hidden,
                               name=f"{prefix}1")
    act = mx.sym.Activation(fc, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=classes,
                                name=f"{prefix}2")
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def _bound_module(sym, feat=6, batch=4):
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind([("data", (batch, feat))], [("softmax_label", (batch,))],
             for_training=False)
    mod.init_params(mx.initializer.Xavier())
    return mod


# ------------------------------------------------------------- primitives
def test_record_tree_and_dedupe():
    tr = trc.new_trace()
    root = trc.record(tr, "serve.request", 0.0, 0.10, model="m")
    a = trc.record(tr, "serve.queue.wait", 0.0, 0.04, parent=root)
    trc.record(tr, "serve.exec", 0.04, 0.10, parent=root)
    # a span id re-recorded (growing session root) dedupes last-wins
    trc.record(tr, "serve.request", 0.0, 0.20, span_id=root, model="m")
    t = trc.tree(tr.trace_id)
    assert t["name"] == "serve.request" and t["dur_us"] == 200000
    assert [c["name"] for c in t["children"]] == \
        ["serve.queue.wait", "serve.exec"]
    assert t["children"][0]["span"] == a
    assert len(trc.spans(tr.trace_id)) == 3      # deduped
    assert tr.root == root


def test_trace_buffer_capacity_bounded():
    trc.configure(capacity=8)
    tr = trc.new_trace()
    for i in range(50):
        trc.record(tr, f"s{i}", 0.0, 0.001)
    assert len(trc.spans()) <= 8


def test_flight_ring_counts_trace_records_under_capacity():
    """Bugfix sweep: trace records ride the flight ring under the
    existing MXNET_FLIGHT_RECORDER_CAPACITY bound — an always-on trace
    plane can never grow the ring unbounded."""
    tm.flightrec.configure(capacity=32)
    try:
        tr = trc.new_trace()
        for i in range(200):
            trc.record(tr, f"s{i}", 0.0, 0.001)
        recs = tm.flightrec.get_records()
        assert len(recs) <= 32
        assert all(r["kind"] == "trace.span" for r in recs)
    finally:
        tm.flightrec.configure(capacity=512)
        tm.flightrec.clear()


def test_sampling_deterministic():
    trc.configure(sample=0.5)
    picks = [trc.sample() for _ in range(10)]
    assert sum(picks) == 5
    trc.configure(sample=0.5)        # reset the counter: same decisions
    assert [trc.sample() for _ in range(10)] == picks
    trc.configure(sample=0.0)
    assert not any(trc.sample() for _ in range(5))
    trc.configure(sample=1.0)
    assert all(trc.sample() for _ in range(5))


# ------------------------------------------------------- serve span trees
def test_served_request_span_tree_deterministic_fakeclock():
    """Acceptance: a served request reconstructs to a single parented
    span tree, byte-deterministic under FakeClock — and batch-mates
    share the dispatch span id."""
    clock = FakeClock()
    sym = _mlp("tr")
    server = mx.serve.serve(_bound_module(sym), ladder=[1, 2, 4],
                            start=False, clock=clock,
                            default_deadline_ms=10)
    rs = np.random.RandomState(0)
    h1 = server.submit({"data": rs.rand(2, 6).astype(np.float32)})
    h2 = server.submit({"data": rs.rand(1, 6).astype(np.float32)})
    assert h1.trace_id and h2.trace_id and h1.trace_id != h2.trace_id
    clock.advance(0.010)
    assert server.pump() == 1

    t = trc.tree(h1.trace_id)
    assert t["name"] == "serve.request"
    assert t["ts_us"] == 0 and t["dur_us"] == 10000   # exact fake time
    assert t["model"] == "default" and t["rows"] == 2
    kids = {c["name"]: c for c in t["children"]}
    assert set(kids) == {"serve.queue.wait", "serve.dispatch"}
    assert kids["serve.queue.wait"]["dur_us"] == 10000
    disp = kids["serve.dispatch"]
    assert disp["n_requests"] == 2 and disp["shared"] is True
    assert [c["name"] for c in disp["children"]] == \
        ["serve.assemble", "serve.exec", "serve.respond"]
    # every span of the tree carries the same trace id
    assert {r["trace"] for r in trc.spans(h1.trace_id)} == {h1.trace_id}

    # the batch-mate's tree shares the dispatch span id, nothing else
    t2 = trc.tree(h2.trace_id)
    disp2 = [c for c in t2["children"] if c["name"] == "serve.dispatch"][0]
    assert disp2["span"] == disp["span"]
    assert t2["span"] != t["span"]

    # the ring mirrored the records (joinable post-mortem)
    ring = [r for r in tm.flightrec.get_records()
            if r["kind"] == "trace.span"]
    assert {r["trace"] for r in ring} >= {h1.trace_id, h2.trace_id}
    disp_ring = [r for r in tm.flightrec.get_records()
                 if r["kind"] == "serve.dispatch"]
    assert disp_ring and set(disp_ring[-1]["trace_ids"]) == \
        {h1.trace_id, h2.trace_id}


def test_session_trace_multi_step_single_tree():
    """Acceptance (stateful-decode shape through serve): N submits that
    join one session trace reconstruct to ONE tree — per-step request
    roots parented under the session root."""
    clock = FakeClock()
    sym = _mlp("ss")
    server = mx.serve.serve(_bound_module(sym), ladder=[1, 2],
                            start=False, clock=clock,
                            default_deadline_ms=5)
    session = trc.new_trace(session=True)
    rs = np.random.RandomState(1)
    for _step in range(3):
        server.submit({"data": rs.rand(1, 6).astype(np.float32)},
                      trace=session)
        clock.advance(0.005)
        assert server.pump() == 1
    t = trc.tree(session.trace_id)
    assert t["name"] == "serve.decode.session"
    steps = [c for c in t["children"] if c["name"] == "serve.request"]
    assert len(steps) == 3
    # one trace id across all N steps; the session root spans them all
    assert {r["trace"] for r in trc.spans(session.trace_id)} == \
        {session.trace_id}
    assert t["dur_us"] == steps[-1]["ts_us"] + steps[-1]["dur_us"] - \
        steps[0]["ts_us"]


def test_shed_request_stamps_trace_ids():
    """Satellite: a shed request is traceable to the queue state that
    doomed it — ShedError.trace_id, the serve.shed ring record's
    trace_ids, and a root span carrying queue depth/watermark."""
    clock = FakeClock()
    sym = _mlp("sh")
    server = mx.serve.serve(_bound_module(sym), ladder=[1, 2, 4],
                            start=False, clock=clock, max_queue=8,
                            shed_watermark=2, default_deadline_ms=1000)
    rs = np.random.RandomState(2)
    h1 = server.submit({"data": rs.rand(1, 6).astype(np.float32)},
                       deadline_ms=1)
    h2 = server.submit({"data": rs.rand(1, 6).astype(np.float32)},
                       deadline_ms=1)
    clock.advance(0.005)            # both queued requests now doomed
    h3 = server.submit({"data": rs.rand(1, 6).astype(np.float32)})
    for h in (h1, h2):
        exc = h.exception()
        assert isinstance(exc, mx.serve.ShedError)
        assert exc.trace_id == h.trace_id
        root = trc.tree(h.trace_id)
        assert root["error"] == "shed"
        assert root["queue_depth"] == 0 and root["shed_depth"] == 2
        assert root["retry_after_ms"] >= 1
        assert [c["name"] for c in root["children"]] == \
            ["serve.queue.wait"]
    shed_recs = [r for r in tm.flightrec.get_records()
                 if r["kind"] == "serve.shed"]
    assert shed_recs and set(shed_recs[-1]["trace_ids"]) == \
        {h1.trace_id, h2.trace_id}
    assert not h3.done()            # the live request kept its slot


def test_breaker_reject_stamps_trace_id():
    """Satellite: a breaker-open rejection leaves a trace-stamped ring
    record and CircuitOpenError.trace_id."""
    clock = FakeClock()
    sym = _mlp("br")
    server = mx.serve.serve(_bound_module(sym), ladder=[1, 2],
                            start=False, clock=clock,
                            breaker_threshold=2)
    entry = server._registry.entry("default")
    now = clock.now()
    entry.breaker.record_failure(now)
    entry.breaker.record_failure(now)
    rs = np.random.RandomState(3)
    with pytest.raises(mx.serve.CircuitOpenError) as ei:
        server.submit({"data": rs.rand(1, 6).astype(np.float32)})
    assert ei.value.trace_id is not None
    root = trc.tree(ei.value.trace_id)
    assert root["name"] == "serve.request"
    assert root["error"] == "circuit_open"
    rej = [r for r in tm.flightrec.get_records()
           if r["kind"] == "serve.breaker.reject"]
    assert rej and rej[-1]["trace"] == ei.value.trace_id


def test_stats_surfaces_exemplar_and_slowest_trace():
    clock = FakeClock()
    sym = _mlp("st")
    server = mx.serve.serve(_bound_module(sym), ladder=[1, 2],
                            start=False, clock=clock,
                            default_deadline_ms=20)
    rs = np.random.RandomState(4)
    h = server.submit({"data": rs.rand(1, 6).astype(np.float32)})
    clock.advance(0.020)
    server.pump()
    m = server.stats()["models"]["default"]
    assert m["p99_trace"] == h.trace_id
    assert m["slowest_trace"]["trace"] == h.trace_id
    assert m["slowest_trace"]["latency_ms"] == pytest.approx(20.0)


# ------------------------------------------------------------- exemplars
def test_prometheus_default_render_byte_identical_golden():
    """Bugfix sweep: exemplar plumbing must not change the default
    exposition format — pinned against the exact expected text."""
    tm.metrics.reset()
    h = tm.histogram("lat.seconds", buckets=(0.1, 1.0), model="m")
    h.observe(0.05, exemplar="t000001")
    h.observe(0.5, exemplar="t000002")
    h.observe(5.0, exemplar="t000003")
    tm.counter("reqs", model="m").inc(3)
    expected = (
        '# TYPE mxnet_lat_seconds histogram\n'
        'mxnet_lat_seconds_bucket{model="m",le="0.1"} 1\n'
        'mxnet_lat_seconds_bucket{model="m",le="1"} 2\n'
        'mxnet_lat_seconds_bucket{model="m",le="+Inf"} 3\n'
        'mxnet_lat_seconds_sum{model="m"} 5.55\n'
        'mxnet_lat_seconds_count{model="m"} 3\n'
        '# TYPE mxnet_reqs_total counter\n'
        'mxnet_reqs_total{model="m"} 3\n')
    assert tm.prometheus.render() == expected
    # the existing parser round-trips the (unchanged) default text
    parsed = tm.prometheus.parse(tm.prometheus.render())
    assert parsed['mxnet_lat_seconds_count{model="m"}'] == 3
    # quantile estimation is untouched by exemplars
    assert h.quantile(0.5) == pytest.approx(0.55, rel=0.02)
    # openmetrics opt-in renders them
    om = tm.prometheus.render(openmetrics=True)
    assert '# {trace_id="t000001"} 0.05' in om
    assert '# {trace_id="t000003"} 5' in om
    tm.metrics.reset()


def test_histogram_exemplar_tracks_quantile_bucket():
    tm.metrics.reset()
    h = tm.histogram("q.seconds", buckets=(0.01, 0.1, 1.0))
    for i in range(99):
        h.observe(0.005, exemplar=f"fast{i}")
    h.observe(0.5, exemplar="slow")
    assert h.exemplar(0.5) == "fast98"
    assert h.exemplar(0.999) == "slow"
    assert h.exemplar(0.99) in ("fast98", "slow")
    tm.metrics.reset()


# ----------------------------------------------------- step attribution
def _fit_mod(prefix, batches=8, batch=8, feat=6, K=1, epochs=1):
    X = np.random.rand(batches * batch, feat).astype(np.float32)
    Y = (np.random.rand(batches * batch) * 3).astype(np.float32)
    it = mx.io.NDArrayIter(X, Y, batch_size=batch)
    mod = mx.mod.Module(_mlp(prefix, feat=feat), context=mx.cpu())
    mod.fit(it, num_epoch=epochs, steps_per_dispatch=K,
            initializer=mx.initializer.Xavier(),
            optimizer_params={"learning_rate": 0.05})
    return mod


def _phase_hist_sums():
    snap = tm.metrics.snapshot()["histograms"]
    out = {}
    for key, rec in snap.items():
        if key.startswith("step.phase."):
            out[key[len("step.phase."):-len(".seconds")]] = rec
    return out


def test_step_phases_sum_to_wall_fused():
    """Acceptance: step.phase.* histograms sum to within 5% of the
    measured step wall time on the fused (K=1) path."""
    tm.metrics.reset()
    sa.configure(armed=True)
    _fit_mod("sp1", batches=8)
    recs = sa.records()
    assert len(recs) == 8
    for r in recs:
        assert r["steps"] == 1
        assert sum(r["phases_us"].values()) == \
            pytest.approx(r["wall_us"], rel=0.05)
    hists = _phase_hist_sums()
    assert set(hists) == set(sa.PHASES)
    assert all(rec["count"] == 8 for rec in hists.values())
    total_wall = sum(r["wall_us"] for r in recs) / 1e6
    total_phases = sum(rec["sum"] for rec in hists.values())
    assert total_phases == pytest.approx(total_wall, rel=0.05)
    # the real phases were attributed, not just folded into "other"
    assert hists["dispatch"]["sum"] > 0 and hists["device"]["sum"] >= 0
    assert hists["data_wait"]["count"] == 8
    assert tm.get_metric("step.count").value == 8


def test_step_phases_sum_to_wall_scan_k4():
    """Acceptance: same 5% gate on the K=4 scan path — one attribution
    record per window, phases divided over the K logical batches, and
    one device block per window only."""
    tm.metrics.reset()
    sa.configure(armed=True)
    _fit_mod("sp4", batches=8, K=4)
    recs = sa.records()
    assert len(recs) == 2 and all(r["steps"] == 4 for r in recs)
    for r in recs:
        assert sum(r["phases_us"].values()) == \
            pytest.approx(r["wall_us"], rel=0.05)
    hists = _phase_hist_sums()
    assert all(rec["count"] == 2 for rec in hists.values())
    total_wall_per_step = sum(r["wall_us"] / r["steps"]
                              for r in recs) / 1e6
    total_phases = sum(rec["sum"] for rec in hists.values())
    assert total_phases == pytest.approx(total_wall_per_step, rel=0.05)
    assert tm.get_metric("step.count").value == 8


def test_step_attribution_unarmed_records_nothing():
    sa.configure(armed=None)
    tm.metrics.reset()
    assert not sa.armed()
    _fit_mod("sp0", batches=4)
    assert sa.records() == []
    assert not _phase_hist_sums()


def test_straggler_detector_flags_with_phase_breakdown():
    """A step k*MAD above the rolling median is flagged with its phase
    breakdown (scripted clock: fully deterministic)."""
    t = [0.0]

    def fake_clock():
        return t[0]

    prev = sa.use_clock(fake_clock)
    sa.configure(armed=True, k_mad=5.0)
    tm.metrics.reset()
    try:
        def one_step(dur, n):
            sa.step_begin(0, n)
            sa.note("assemble", dur * 0.25)
            sa.note("dispatch", dur * 0.25)
            t[0] += dur
            sa.step_end()

        for n in range(20):
            one_step(0.010, n)
        assert sa.stragglers() == []
        one_step(0.200, 20)              # 20x the median: a stall
        strag = sa.stragglers()
        assert len(strag) == 1
        rec = strag[0]
        assert rec["nbatch"] == 20 and rec["straggler"]
        assert rec["wall_us"] == 200000
        assert rec["median_us"] == 10000
        assert rec["phases_us"]["assemble"] == 50000
        assert rec["phases_us"]["other"] == 100000
        assert tm.get_metric("step.stragglers").value == 1
        ring = [r for r in tm.flightrec.get_records()
                if r["kind"] == "step.straggler"]
        assert ring and ring[-1]["wall_us"] == 200000
        assert ring[-1]["assemble_us"] == 50000
    finally:
        sa.use_clock(prev)
        sa.configure(armed=None, k_mad=5.0)
        sa.reset()


# ------------------------------------------------- decode session traces
def test_kv_cache_decoder_single_trace_across_steps():
    """Acceptance: a multi-step stateful decode carries ONE trace —
    every token step a child span of the session root; reset() rotates
    to a fresh session."""
    from mxnet_tpu.models import transformer as tfm
    V, D, H, T, B = 64, 32, 4, 8, 4
    full_sym = tfm.get_symbol(vocab_size=V, d_model=D, n_layer=1,
                              n_head=H, seq_len=T, include_loss=False,
                              max_seq_len=T)
    full = mx.mod.Module(full_sym, label_names=[])
    full.bind([("data", (B, T))], None, for_training=False)
    full.init_params(mx.initializer.Xavier(magnitude=2.0))
    args, _ = full.get_params()
    dec_sym = tfm.get_decode_symbol(vocab_size=V, d_model=D, n_layer=1,
                                    n_head=H, capacity=T, max_seq_len=T)
    dec = mx.mod.Module(dec_sym, label_names=[])
    dec.bind([("data", (B, 1))], None, for_training=False)
    dec.init_params(initializer=None, arg_params=args, aux_params={},
                    allow_missing=True)
    drv = tfm.KVCacheDecoder(dec, capacity=T)
    sid = drv.trace.trace_id
    tokens = np.random.RandomState(5).randint(0, V, (B, T)).astype(
        np.int32)
    for step in range(4):
        drv.step(tokens[:, step:step + 1])
    t = trc.tree(sid)
    assert t["name"] == "lm.decode.session"
    steps = [c for c in t["children"] if c["name"] == "lm.decode.step"]
    assert len(steps) == 4
    assert [s["pos"] for s in steps] == [0, 1, 2, 3]
    assert {r["trace"] for r in trc.spans(sid)} == {sid}
    # the session root grew across steps: it covers first -> last
    assert t["dur_us"] >= steps[-1]["ts_us"] + steps[-1]["dur_us"] - \
        t["ts_us"] - 1
    drv.reset()
    assert drv.trace.trace_id != sid     # a new sequence = a new trace
    drv.step(tokens[:, :1])
    t2 = trc.tree(drv.trace.trace_id)
    assert len([c for c in t2["children"]
                if c["name"] == "lm.decode.step"]) == 1


# ----------------------------------------------------- exporters / tools
def test_dump_profile_includes_serve_and_step_tracks(tmp_path):
    """Satellite: profiler.dump_profile's chrome trace carries the new
    track names — serve.trace/* lanes and the step.phase lane."""
    clock = FakeClock()
    sym = _mlp("dp")
    server = mx.serve.serve(_bound_module(sym), ladder=[1, 2],
                            start=False, clock=clock,
                            default_deadline_ms=10)
    h = server.submit({"data": np.random.RandomState(6)
                       .rand(1, 6).astype(np.float32)})
    clock.advance(0.010)
    server.pump()
    sa.configure(armed=True)
    _fit_mod("dpf", batches=4)
    sa.configure(armed=None)

    path = str(tmp_path / "profile.json")
    mx.profiler.profiler_set_config(filename=path)
    out = mx.profiler.dump_profile()
    with open(out) as f:
        doc = json.load(f)
    events = doc["traceEvents"]
    tracks = {e["args"]["name"] for e in events
              if e.get("ph") == "M" and e["name"] == "thread_name"}
    assert f"serve.trace/{h.trace_id}" in tracks
    assert "step.phase" in tracks
    xnames = {e["name"] for e in events if e.get("ph") == "X"}
    assert "serve.request" in xnames and "serve.dispatch" in xnames
    assert "step" in xnames
    assert any(n.startswith("step.phase.") for n in xnames)
    # phase events nest inside their step interval on the step lane
    steps = [e for e in events if e.get("ph") == "X"
             and e["name"] == "step"]
    phases = [e for e in events if e.get("ph") == "X"
              and e["name"].startswith("step.phase.")]
    assert steps and phases
    s0 = steps[0]
    inside = [p for p in phases
              if s0["ts"] <= p["ts"] <= s0["ts"] + s0["dur"] + 1]
    assert inside


def test_jsonl_and_diagnose_render_traces_sections(tmp_path):
    """Satellite: tools/diagnose.py renders the traces section (request
    trees, step-phase table, stragglers) in BOTH the jsonl and the
    crash paths."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import diagnose

    clock = FakeClock()
    sym = _mlp("dg")
    server = mx.serve.serve(_bound_module(sym), ladder=[1, 2],
                            start=False, clock=clock,
                            default_deadline_ms=25)
    h = server.submit({"data": np.random.RandomState(7)
                       .rand(1, 6).astype(np.float32)})
    clock.advance(0.025)
    server.pump()
    sa.configure(armed=True)
    _fit_mod("dgf", batches=4)
    sa.configure(armed=None)
    # a scripted straggler so the list renders
    t = [0.0]
    prev = sa.use_clock(lambda: t[0])
    try:
        sa.configure(armed=True)
        for n in range(16):
            sa.step_begin(1, n)
            t[0] += 0.01
            sa.step_end()
        sa.step_begin(1, 16)
        t[0] += 0.3
        sa.step_end()
    finally:
        sa.use_clock(prev)
        sa.configure(armed=None)

    # jsonl path
    jl = tm.jsonl.dump(str(tmp_path / "ev.jsonl"))
    with open(jl) as f:
        lines = f.read().splitlines()
    trace_lines = [json.loads(l) for l in lines
                   if json.loads(l).get("type") == "trace"]
    assert {r["trace"] for r in trace_lines} >= {h.trace_id}
    report = diagnose.render_file(jl)
    assert "traces:" in report
    assert "serve.request" in report and "serve.queue.wait" in report
    assert "step phases (per logical batch):" in report
    assert "stragglers:" in report

    # crash path (ring-mirrored records)
    tm.flightrec.configure(dump_dir=str(tmp_path))
    crash = tm.flightrec.dump_crash(where="test_trace")
    report2 = diagnose.render_file(crash)
    assert "traces:" in report2
    assert "serve.request" in report2
    assert "step phases (per logical batch):" in report2
    assert "stragglers:" in report2


# ------------------------------------------------------------- perfwatch
def _perfwatch():
    import importlib
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    return importlib.import_module("perfwatch")


def test_perfwatch_passes_on_real_history(capsys):
    """Acceptance: the watchdog passes on the repo's real BENCH history
    and recorded benchmark gates."""
    pw = _perfwatch()
    assert pw.main(["--check"]) == 0
    out = capsys.readouterr().out
    assert "perfwatch OK" in out


def test_perfwatch_flags_seeded_regression(tmp_path, capsys):
    """Acceptance: a doctored bench payload (cpu-fallback shaped, rates
    halved) exits nonzero naming the regressed metrics."""
    pw = _perfwatch()
    good = {"metric": "resnet20_cifar_b32_train_img_per_sec_cpu_fallback",
            "value": 1000.0, "unit": "img/s", "vs_baseline": None,
            "serve": {"req_per_sec": 140.0,
                      "latency_ms": {"p99": 60.0}},
            "lm": {"train_tokens_per_sec": 5000.0,
                   "decode_tokens_per_sec": 800.0, "max_context": 262144}}
    bad = json.loads(json.dumps(good))
    bad["value"] = 400.0                      # past even the 50% fallback
    bad["serve"]["req_per_sec"] = 30.0        # tolerance for these rows
    bad["lm"]["max_context"] = 1024
    (tmp_path / "BENCH_r01.json").write_text(
        json.dumps({"parsed": good}))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(bad))
    rc = pw.main(["--history", str(tmp_path), "--no-gates"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "REGRESSION" in out
    assert "serve.req_per_sec" in out
    assert "lm.max_context" in out
    assert out.count("REGRESSION") == 3


def test_perfwatch_first_sample_and_nulls_pass(tmp_path):
    pw = _perfwatch()
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(
        {"parsed": {"metric": "m_a", "value": None,
                    "error": "backend unavailable"}}))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(
        {"parsed": {"metric": "m_b", "value": 10.0}}))
    rc = pw.main(["--history", str(tmp_path), "--no-gates"])
    assert rc == 0                   # first sample of a series: vacuous


def test_perfwatch_rechecks_recorded_gates(tmp_path, capsys):
    pw = _perfwatch()
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(
        {"parsed": {"metric": "m", "value": 1.0}}))
    results = tmp_path / "results"
    results.mkdir()
    (results / "someline.json").write_text(json.dumps({
        "gate_pct": 2.0, "analytic_overhead_pct": 3.5,
        "nested": {"gate_pass": False}}))
    rc = pw.main(["--history", str(tmp_path),
                  "--results", str(results)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "GATE FAIL" in out
    assert "analytic_overhead_pct" in out
    assert "nested.gate_pass" in out


def test_perfwatch_parses_bench_stdout_tail(tmp_path):
    """--payload accepts a bench.py stdout capture: the last JSON line
    is the payload (the one-JSON-line contract)."""
    pw = _perfwatch()
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(
        {"parsed": {"metric": "m", "value": 100.0}}))
    stdout = ("[bench +1s] warmup\nnot json\n" +
              json.dumps({"metric": "m", "value": 10.0}) + "\n")
    payload = tmp_path / "stdout.txt"
    payload.write_text(stdout)
    rc = pw.main(["--history", str(tmp_path), "--no-gates",
                  "--payload", str(payload)])
    assert rc == 1                   # 10 vs best prior 100: regression
    rc2 = pw.main(["--history", str(tmp_path), "--no-gates",
                   "--payload", str(payload), "--tolerance", "0.95"])
    assert rc2 == 0                  # tolerance widens the gate
