"""Data-pipeline: im2rec CLI, prefetch-to-device, throughput floor.

reference: tools/im2rec.py packing contract + src/io/iter_prefetcher.h's
prefetch-to-staging behavior; the throughput floor guards against the
pipeline regressing into per-image device round-trips (which once cut
throughput ~80x).
"""
import os
import subprocess
import sys
import tempfile
import time

import numpy as np

import mxnet_tpu as mx

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_image(path, arr):
    try:
        import cv2
        cv2.imwrite(path, arr[:, :, ::-1])
    except ImportError:
        from PIL import Image
        Image.fromarray(arr).save(path)


def test_im2rec_list_pack_read_roundtrip(tmp_path):
    rng = np.random.RandomState(0)
    root = tmp_path / "imgs"
    for cls in ("cats", "dogs"):
        (root / cls).mkdir(parents=True)
        for i in range(3):
            arr = rng.randint(0, 255, (40, 48, 3), dtype=np.uint8)
            _write_image(str(root / cls / f"{i}.png"), arr)
    prefix = str(tmp_path / "pack")
    cli = os.path.join(ROOT, "tools", "im2rec.py")
    r = subprocess.run([sys.executable, cli, "--list", prefix, str(root)],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    lines = open(prefix + ".lst").read().strip().splitlines()
    assert len(lines) == 6
    r = subprocess.run([sys.executable, cli, prefix, str(root),
                        "--resize", "36"], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert os.path.exists(prefix + ".rec")
    assert os.path.exists(prefix + ".idx")

    it = mx.image.ImageIter(2, (3, 32, 32), path_imgrec=prefix + ".rec")
    seen, labels = 0, set()
    for batch in it:
        seen += batch.data[0].shape[0] - batch.pad
        labels.update(np.asarray(batch.label[0].asnumpy()).astype(
            int).tolist())
    assert seen == 6
    assert labels == {0, 1}


def test_prefetching_iter_to_device():
    X = np.random.rand(32, 3, 8, 8).astype("f")
    y = np.arange(32, dtype="f")
    base = mx.io.NDArrayIter(X, y, batch_size=8)
    it = mx.io.PrefetchingIter(base, device=mx.cpu())
    n = 0
    for batch in it:
        assert batch.data[0].shape == (8, 3, 8, 8)
        dev = next(iter(batch.data[0].asjax().devices()))
        assert dev.platform == "cpu"
        n += 1
    assert n == 4
    it.reset()
    assert sum(1 for _ in it) == 4


def test_pipeline_throughput_floor(tmp_path):
    """Guards the no-device-round-trips invariant: even one CPU core must
    sustain far more than single-digit img/s."""
    sys.path.insert(0, os.path.join(ROOT, "benchmarks"))
    import io_bench
    prefix = str(tmp_path / "synth")
    io_bench.make_synthetic_pack(prefix, 64, 128)
    img_s = io_bench.measure_threads(prefix, 16, (3, 112, 112), epochs=1)
    assert img_s > 25, f"pipeline throughput collapsed: {img_s:.1f} img/s"
    mp_res = io_bench.measure_mp(prefix, 16, (3, 112, 112), epochs=1,
                                 num_workers=2)
    assert mp_res is not None and mp_res[0] > 25, \
        f"mp pipeline throughput collapsed: {mp_res}"
