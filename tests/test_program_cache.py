"""Process-wide program cache (mxnet_tpu/program_cache.py).

Rebinding the same (symbol, shapes, dtypes, ctx kind) must reuse jitted
programs instead of re-tracing per Executor instance — asserted through
the executor.jit_cache.hit/miss telemetry counters and the
executor.jit_cache.programs_live gauge (ISSUE 3 tentpole part 2).
"""
import numpy as np

import mxnet_tpu as mx


def _mlp():
    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data=data, num_hidden=8, name="fc1")
    act = mx.sym.Activation(fc, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=3, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def _counters():
    c = mx.telemetry.snapshot()["counters"]
    return (c.get("executor.jit_cache.hit", 0),
            c.get("executor.jit_cache.miss", 0))


def _batch(rs, with_label=True):
    data = [mx.nd.array(rs.rand(4, 6).astype(np.float32))]
    label = [mx.nd.array(rs.randint(0, 3, (4,)).astype(np.float32))] \
        if with_label else None
    return mx.io.DataBatch(data, label)


def test_rebind_train_eval_reuses_programs():
    """A second module bound over the same symbol/shapes (the train→eval
    rebind pattern) must hit the process cache — no new trace/compile."""
    mx.program_cache.clear()
    mx.telemetry.reset()
    mx.telemetry.enable()
    try:
        rs = np.random.RandomState(0)
        sym = _mlp()
        m1 = mx.mod.Module(sym, context=mx.cpu())
        m1.bind([("data", (4, 6))], [("softmax_label", (4,))])
        m1.init_params(mx.initializer.Xavier())
        m1.forward(_batch(rs), is_train=False)
        _ = m1.get_outputs()[0].asnumpy()
        hit0, miss0 = _counters()
        assert miss0 >= 1 and hit0 == 0

        # fresh executor, same signature -> process-cache hit
        m2 = mx.mod.Module(sym, context=mx.cpu())
        m2.bind([("data", (4, 6))], [("softmax_label", (4,))],
                for_training=False)
        m2.init_params(mx.initializer.Xavier())
        m2.forward(_batch(rs), is_train=False)
        _ = m2.get_outputs()[0].asnumpy()
        hit1, miss1 = _counters()
        assert hit1 > hit0, "eval rebind must reuse the cached program"
        assert miss1 == miss0, "eval rebind must not compile anything"
        gauges = mx.telemetry.snapshot()["gauges"]
        assert gauges.get("executor.jit_cache.programs_live", 0) >= 1
    finally:
        mx.telemetry.disable()


def test_fused_step_cached_across_rebinds():
    """force_rebind + re-init of the same training arrangement reuses
    the fused fwd+bwd+update program (same optimizer token)."""
    mx.program_cache.clear()
    mx.telemetry.reset()
    mx.telemetry.enable()
    try:
        rs = np.random.RandomState(0)
        sym = _mlp()

        def train_two_batches(mod):
            mod.bind([("data", (4, 6))], [("softmax_label", (4,))],
                     force_rebind=True)
            mod.init_params(mx.initializer.Xavier(), force_init=True)
            mod.init_optimizer(
                optimizer_params=(("learning_rate", 0.1),
                                  ("momentum", 0.9)), force_init=True)
            assert mod._fused_armed
            for _ in range(2):
                mod.forward_backward(_batch(rs))
                mod.update()

        train_two_batches(mx.mod.Module(sym, context=mx.cpu()))
        hit0, miss0 = _counters()
        train_two_batches(mx.mod.Module(sym, context=mx.cpu()))
        hit1, miss1 = _counters()
        assert hit1 > hit0
        assert miss1 == miss0, "rebind recompiled the fused step"
    finally:
        mx.telemetry.disable()


def test_bucketing_and_eval_rebind_cache_accounting():
    """Acceptance: rebinding train→eval plus cycling 3 buckets twice
    records jit_cache.hit >= 4 with ZERO new compiles on the second
    bucket cycle (revisited buckets replay their compiled programs)."""
    def sym_gen(seq_len):
        data = mx.sym.var("data")
        emb = mx.sym.Embedding(data, input_dim=20, output_dim=4,
                               name="emb")
        pooled = mx.sym.sum(emb, axis=1)
        fc = mx.sym.FullyConnected(pooled, num_hidden=3, name="fc")
        return (mx.sym.SoftmaxOutput(fc, name="softmax"),
                ["data"], ["softmax_label"])

    mx.program_cache.clear()
    mx.telemetry.reset()
    mx.telemetry.enable()
    try:
        rng = np.random.RandomState(0)
        mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=10,
                                     context=mx.cpu())
        mod.bind([("data", (8, 10))], [("softmax_label", (8,))])
        mod.init_params()
        mod.init_optimizer(optimizer_params={"learning_rate": 0.1})

        def one_batch(key):
            batch = mx.io.DataBatch(
                data=[mx.nd.array(rng.randint(0, 20, (8, key))
                                  .astype(np.float32))],
                label=[mx.nd.array(rng.randint(0, 3, 8)
                                   .astype(np.float32))],
                bucket_key=key,
                provide_data=[mx.io.DataDesc("data", (8, key))],
                provide_label=[mx.io.DataDesc("softmax_label", (8,))])
            mod.forward_backward(batch)
            mod.update()

        for key in (10, 6, 4):             # first cycle: compiles
            one_batch(key)
        hit0, miss0 = _counters()
        for key in (10, 6, 4):             # second cycle: replays
            one_batch(key)
        hit1, miss1 = _counters()
        assert miss1 == miss0, "revisited buckets must not recompile"
        bucket_hits = hit1 - hit0
        assert bucket_hits >= 3

        # validation pass on the train module compiles fwd_infer once...
        eval_batch = mx.io.DataBatch(
            data=[mx.nd.array(rng.randint(0, 20, (8, 10))
                              .astype(np.float32))],
            label=[mx.nd.array(rng.randint(0, 3, 8).astype(np.float32))],
            bucket_key=10,
            provide_data=[mx.io.DataDesc("data", (8, 10))],
            provide_label=[mx.io.DataDesc("softmax_label", (8,))])
        mod.forward(eval_batch, is_train=False)
        _ = mod.get_outputs()[0].asnumpy()

        # ...so a separate eval-bound module over the same symbol/shapes
        # (the train→eval rebind) reuses it from the process cache. The
        # symbol OBJECT is reused, as real rebind flows do — regenerating
        # it would draw fresh auto-names and change the signature.
        sym = mod._buckets[10].symbol
        ev = mx.mod.Module(sym, context=mx.cpu())
        ev.bind([("data", (8, 10))], [("softmax_label", (8,))],
                for_training=False)
        ev.init_params(allow_missing=False, force_init=True,
                       arg_params=mod.get_params()[0],
                       aux_params=mod.get_params()[1])
        ev.forward(mx.io.DataBatch(
            data=[mx.nd.array(rng.randint(0, 20, (8, 10))
                              .astype(np.float32))],
            label=[mx.nd.array(rng.randint(0, 3, 8).astype(np.float32))]),
            is_train=False)
        _ = ev.get_outputs()[0].asnumpy()
        hit2, miss2 = _counters()
        assert hit2 >= 4, f"expected >= 4 cache hits, saw {hit2}"
    finally:
        mx.telemetry.disable()


def test_cache_key_dtype_negative():
    """A compute-dtype change must MISS: the traced program differs, and
    a false hit would silently run the wrong-precision program."""
    import jax.numpy as jnp
    mx.program_cache.clear()
    mx.telemetry.reset()
    mx.telemetry.enable()
    try:
        rs = np.random.RandomState(0)
        sym = _mlp()
        for dtype in (None, jnp.bfloat16):
            mod = mx.mod.Module(sym, context=mx.cpu(),
                                compute_dtype=dtype)
            mod.bind([("data", (4, 6))], [("softmax_label", (4,))])
            mod.init_params(mx.initializer.Xavier())
            mod.forward(_batch(rs), is_train=False)
            _ = mod.get_outputs()[0].asnumpy()
        hit, miss = _counters()
        assert miss >= 2, "dtype change must miss the cache"
        assert hit == 0, "dtype change must not hit the f32 program"
    finally:
        mx.telemetry.disable()


def test_cache_key_mesh_topology_negative():
    """A device-topology change (1 -> 8 host-platform devices in one
    process) must MISS: compiled programs bake in their mesh's
    collective structure (psum shard counts, ZeRO reduce-scatter
    shapes), so reusing a 1-device trace on an 8-device mesh — or vice
    versa — silently runs the wrong program."""
    import jax
    if len(jax.devices("cpu")) < 8:
        import pytest
        pytest.skip("needs 8 virtual cpu devices")
    mx.program_cache.clear()
    mx.telemetry.reset()
    mx.telemetry.enable()
    try:
        rs = np.random.RandomState(0)
        sym = _mlp()
        keys = []
        for n_dev in (1, 8):
            mod = mx.mod.Module(sym,
                                context=[mx.cpu(i) for i in range(n_dev)])
            mod.bind([("data", (8, 6))], [("softmax_label", (8,))])
            mod.init_params(mx.initializer.Xavier())
            mod.init_optimizer(optimizer_params={"learning_rate": 0.1})
            assert mod._fused_armed
            data = [mx.nd.array(rs.rand(8, 6).astype(np.float32))]
            label = [mx.nd.array(rs.randint(0, 3, (8,))
                                 .astype(np.float32))]
            mod.forward_backward(mx.io.DataBatch(data, label))
            mod.update()
            keys.append(mod._exec_group._fused_cache_key)
        assert keys[0] is not None and keys[1] is not None
        assert keys[0] != keys[1], \
            "mesh topology must be part of the program-cache key"
        hit, miss = _counters()
        assert hit == 0, "the 8-device bind must not reuse the " \
            "1-device program"
        # spmd spec sets key separately from the plain data mesh
        mod = mx.mod.Module(sym, context=[mx.cpu(i) for i in range(8)])
        mod.bind([("data", (8, 6))], [("softmax_label", (8,))], spmd=True)
        mod.init_params(mx.initializer.Xavier())
        mod.init_optimizer(kvstore=None,
                           optimizer_params={"learning_rate": 0.1})
        assert mod._exec_group._fused_cache_key not in keys
    finally:
        mx.telemetry.disable()


def test_lru_eviction_and_gauge():
    """The cache is a bounded LRU; the programs_live gauge tracks it."""
    mx.program_cache.clear()
    for i in range(5):
        mx.program_cache.put(("k", i), object())
    assert mx.program_cache.size() == 5
    assert mx.program_cache.get(("k", 0)) is not None
    import os
    os.environ["MXNET_PROGRAM_CACHE_SIZE"] = "3"
    try:
        mx.program_cache.put(("k", 5), object())   # triggers eviction
        assert mx.program_cache.size() == 3
        # ("k", 0) was freshly used -> survives; ("k", 1) was LRU -> gone
        assert mx.program_cache.get(("k", 0)) is not None
        assert mx.program_cache.get(("k", 1)) is None
    finally:
        del os.environ["MXNET_PROGRAM_CACHE_SIZE"]
    gauges = mx.telemetry.snapshot()["gauges"]
    assert gauges.get("executor.jit_cache.programs_live") == 3


def test_pin_exempts_from_eviction_and_compile_count():
    """Serving warmup APIs (ISSUE 8): pinned entries survive LRU
    pressure; compile_count() counts fresh insertions monotonically."""
    mx.program_cache.clear()
    c0 = mx.program_cache.compile_count()
    for i in range(4):
        mx.program_cache.put(("p", i), object())
    assert mx.program_cache.compile_count() == c0 + 4
    mx.program_cache.put(("p", 0), object())       # overwrite: no compile
    assert mx.program_cache.compile_count() == c0 + 4
    assert mx.program_cache.pin(("p", 0))
    assert not mx.program_cache.pin(("ghost",))    # absent: not pinned
    assert mx.program_cache.contains(("p", 0))
    assert ("p", 0) in mx.program_cache.pinned()

    import os
    os.environ["MXNET_PROGRAM_CACHE_SIZE"] = "2"
    try:
        mx.program_cache.put(("p", 9), object())
        # ("p", 0) is the LRU entry but pinned -> survives; unpinned
        # oldest entries went instead
        assert mx.program_cache.contains(("p", 0))
        assert mx.program_cache.size() == 2
        # fully-pinned cache overflows rather than break a pin
        mx.program_cache.pin(("p", 9))
        mx.program_cache.put(("p", 10), object())
        mx.program_cache.pin(("p", 10))
        mx.program_cache.put(("p", 11), object())
        assert mx.program_cache.contains(("p", 0))
        assert mx.program_cache.contains(("p", 9))
        assert mx.program_cache.contains(("p", 10))
    finally:
        del os.environ["MXNET_PROGRAM_CACHE_SIZE"]
    mx.program_cache.unpin(("p", 0))
    assert ("p", 0) not in mx.program_cache.pinned()
    mx.program_cache.clear()
    assert not mx.program_cache.pinned()


def test_bucketing_module_inference_cache_contract():
    """ISSUE 8 satellite: BucketingModule in inference mode
    (for_training=False) over the process-wide program cache — the
    second bucket cycle runs entirely from cache (zero new compiles),
    the contract the serving bucket ladder depends on."""
    mx.program_cache.clear()
    mx.telemetry.reset()
    mx.telemetry.enable()
    try:
        rs = np.random.RandomState(0)
        sym = _mlp()
        buckets = [2, 4, 8]
        bm = mx.mod.BucketingModule(
            sym_gen=lambda key: (sym, ["data"], ["softmax_label"]),
            default_bucket_key=max(buckets), context=mx.cpu())
        bm.bind([("data", (8, 6))], [("softmax_label", (8,))],
                for_training=False)
        bm.init_params(mx.initializer.Xavier())
        # warm_buckets binds every rung up front (serving warmup path)
        bm.warm_buckets([(b, [("data", (b, 6))],
                          [("softmax_label", (b,))]) for b in buckets])
        assert sorted(bm.bucket_keys) == buckets

        def cycle():
            outs = {}
            for b in buckets:
                batch = mx.io.DataBatch(
                    [mx.nd.array(np.ones((b, 6), np.float32))],
                    [mx.nd.array(np.zeros((b,), np.float32))],
                    bucket_key=b,
                    provide_data=[("data", (b, 6))],
                    provide_label=[("softmax_label", (b,))])
                bm.forward(batch, is_train=False)
                outs[b] = bm.get_outputs()[0].asnumpy()
            return outs

        first = cycle()
        compiles_mark = mx.program_cache.compile_count()
        _, miss_mark = _counters()
        second = cycle()
        assert mx.program_cache.compile_count() == compiles_mark, \
            "second bucket cycle must not insert new programs"
        _, miss2 = _counters()
        assert miss2 == miss_mark, \
            "second bucket cycle must be all cache hits"
        for b in buckets:
            np.testing.assert_array_equal(first[b], second[b])

        # a FRESH BucketingModule over the same symbol/shapes also runs
        # compile-free (the cache is process-wide, not per instance)
        bm2 = mx.mod.BucketingModule(
            sym_gen=lambda key: (sym, ["data"], ["softmax_label"]),
            default_bucket_key=max(buckets), context=mx.cpu())
        bm2.bind([("data", (8, 6))], [("softmax_label", (8,))],
                 for_training=False)
        bm2.init_params(mx.initializer.Xavier())
        bm2.warm_buckets([(b, [("data", (b, 6))],
                           [("softmax_label", (b,))]) for b in buckets])
        cycle_mark = mx.program_cache.compile_count()
        for b in buckets:
            batch = mx.io.DataBatch(
                [mx.nd.array(np.ones((b, 6), np.float32))], None,
                bucket_key=b, provide_data=[("data", (b, 6))],
                provide_label=[("softmax_label", (b,))])
            bm2.forward(batch, is_train=False)
            bm2.get_outputs()[0].asnumpy()
        assert mx.program_cache.compile_count() == cycle_mark
    finally:
        mx.telemetry.disable()
