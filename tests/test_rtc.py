"""mx.rtc — user Pallas kernels (reference: mx.rtc nvrtc bridge,
src/common/mxrtc.cc:1-141, tests/python/gpu/test_rtc.py).

On the CPU test mesh kernels run in Pallas interpret mode; on TPU the same
code Mosaic-compiles. Numerics are gated against XLA compositions.
"""
import numpy as np
import jax.numpy as jnp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import (assert_almost_equal,
                                  check_numeric_gradient)


def test_rtc_imperative_push():
    """Reference-shaped API: Rtc(name, inputs, outputs, kernel) + push."""
    x = mx.nd.array(np.random.RandomState(0).rand(8, 128).astype("f"))
    y = mx.nd.array(np.random.RandomState(1).rand(8, 128).astype("f"))
    out = mx.nd.empty((8, 128))

    def axpb_kernel(x_ref, y_ref, o_ref):
        o_ref[...] = 2.0 * x_ref[...] + y_ref[...]

    rtc = mx.rtc.Rtc("axpb", [("x", x), ("y", y)], [("out", out)],
                     axpb_kernel)
    rtc.push([x, y], [out])
    assert_almost_equal(out, 2 * x.asnumpy() + y.asnumpy(), rtol=1e-6)
    with pytest.raises(mx.MXNetError):
        rtc.push([x, y], [out], grid_dims=(1, 1, 1))


def test_register_pallas_op_forward_and_graph():
    """A registered kernel is a first-class op: nd namespace, symbolic
    graphs, jitted executor."""
    if "scaled_sub_pl" not in mx.sym.__dict__:
        mx.rtc.register_pallas_op(
            "scaled_sub_pl",
            kernel=lambda attrs: (
                lambda a_ref, b_ref, o_ref: o_ref.__setitem__(
                    ..., a_ref[...] - float(attrs.get("scale", 1.0)) *
                    b_ref[...])),
            out_shapes=lambda attrs, shapes: [(shapes[0], None)],
            inputs=("a", "b"),
            attr_spec={"scale": (float, 1.0)})
        mx.sym._init_symbol_module(mx.sym.__dict__)
        from mxnet_tpu import _op_gen
        _op_gen.init_ndarray_module(mx.nd.__dict__)

    a = np.random.RandomState(2).rand(16, 128).astype("f")
    b = np.random.RandomState(3).rand(16, 128).astype("f")
    # imperative
    out = mx.nd.scaled_sub_pl(mx.nd.array(a), mx.nd.array(b), scale=3.0)
    assert_almost_equal(out, a - 3.0 * b, rtol=1e-6, atol=1e-6)
    # symbolic, inside a jitted executor graph mixed with XLA ops
    sa, sb = mx.sym.var("a"), mx.sym.var("b")
    sym = mx.sym.relu(mx.sym.scaled_sub_pl(sa, sb, scale=3.0))
    exe = sym.bind(mx.cpu(), args={"a": mx.nd.array(a),
                                   "b": mx.nd.array(b)}, grad_req="null")
    exe.forward(is_train=False)
    assert_almost_equal(exe.outputs[0], np.maximum(a - 3.0 * b, 0),
                        rtol=1e-6, atol=1e-6)


def test_register_pallas_op_custom_vjp():
    """User backward kernel -> differentiable graph op."""
    if "sq_scale_pl" not in mx.sym.__dict__:
        def fwd_kernel(attrs):
            s = float(attrs.get("scale", 1.0))

            def k(x_ref, o_ref):
                o_ref[...] = s * x_ref[...] * x_ref[...]
            return k

        def bwd_kernel(attrs):
            s = float(attrs.get("scale", 1.0))

            def k(x_ref, ct_ref, gx_ref):
                gx_ref[...] = 2.0 * s * x_ref[...] * ct_ref[...]
            return k

        mx.rtc.register_pallas_op(
            "sq_scale_pl", kernel=fwd_kernel,
            out_shapes=lambda attrs, shapes: [(shapes[0], None)],
            inputs=("data",), vjp_kernel=bwd_kernel,
            attr_spec={"scale": (float, 1.0)})
        mx.sym._init_symbol_module(mx.sym.__dict__)

    x = np.random.RandomState(4).rand(8, 128).astype("f") + 0.2
    sym = mx.sym.sq_scale_pl(mx.sym.var("data"), scale=1.5)
    check_numeric_gradient(sym, {"data": x}, numeric_eps=1e-2, rtol=0.05)


def test_pallas_sgd_mom_matches_xla_composition():
    """The built-in fused Pallas SGD-momentum kernel == the registry's XLA
    sgd_mom_update op, including rescale/clip/wd, across shapes that
    exercise padding and multi-tile grids."""
    rng = np.random.RandomState(5)
    for shape in [(7,), (50, 33), (4100,), (3, 5, 7)]:
        w = rng.rand(*shape).astype("f")
        g = (rng.rand(*shape).astype("f") - 0.5) * 10
        m = rng.rand(*shape).astype("f")
        kw = dict(lr=0.05, momentum=0.9, wd=0.01, rescale_grad=0.5,
                  clip_gradient=2.0)
        new_w, new_m = mx.rtc.pallas_sgd_mom_update(
            jnp.asarray(w), jnp.asarray(g), jnp.asarray(m), **kw)
        # XLA composition (ops/optimizer_op.py mutates in place)
        wx = mx.nd.array(w)
        mx_m = mx.nd.array(m)
        mx.nd.sgd_mom_update(wx, mx.nd.array(g), mx_m, out=wx, **kw)
        assert_almost_equal(np.asarray(new_w), wx.asnumpy(), rtol=1e-5,
                            atol=1e-6)
        assert_almost_equal(np.asarray(new_m), mx_m.asnumpy(), rtol=1e-5,
                            atol=1e-6)
    # registered-op surface
    w = rng.rand(33).astype("f")
    g = rng.rand(33).astype("f")
    m = np.zeros(33, "f")
    ow, om = mx.nd.pallas_sgd_mom_update(
        mx.nd.array(w), mx.nd.array(g), mx.nd.array(m), lr=0.1,
        momentum=0.9)
    assert_almost_equal(om, -0.1 * g, rtol=1e-6, atol=1e-7)
    assert_almost_equal(ow, w - 0.1 * g, rtol=1e-6, atol=1e-7)


def test_flash_attention_matches_xla():
    """Pallas flash attention == the XLA composition, fwd + grad,
    causal and full, across block configs."""
    import jax
    from mxnet_tpu.rtc import flash_attention
    from mxnet_tpu.parallel.ring_attention import attention

    rng = np.random.RandomState(11)
    q, k, v = [jnp.asarray(rng.normal(0, 1, (2, 2, 256, 32)).astype("f"))
               for _ in range(3)]
    for causal in (False, True):
        for bq, bk in [(128, 128), (128, 64), (64, 128)]:
            out = flash_attention(q, k, v, causal=causal, block_q=bq,
                                  block_k=bk)
            ref = attention(q, k, v, causal=causal)
            assert_almost_equal(np.asarray(out), np.asarray(ref),
                                rtol=1e-5, atol=1e-5)
    # gradients flow through the custom_vjp (recompute backward)
    for causal in (False, True):
        g = jax.grad(lambda a: float(0) + (flash_attention(
            a, k, v, causal=causal) ** 2).sum())(q)
        gr = jax.grad(lambda a: (attention(a, k, v, causal=causal)
                                 ** 2).sum())(q)
        assert_almost_equal(np.asarray(g), np.asarray(gr), rtol=1e-4,
                            atol=1e-5)
    # registered-op surface
    out = mx.nd.pallas_flash_attention(
        mx.nd.array(np.asarray(q)), mx.nd.array(np.asarray(k)),
        mx.nd.array(np.asarray(v)), causal=True)
    assert_almost_equal(out, np.asarray(attention(q, k, v, causal=True)),
                        rtol=1e-5, atol=1e-5)
    with pytest.raises(mx.MXNetError):
        flash_attention(q[:, :, :100], k[:, :, :100], v[:, :, :100],
                        block_q=64, block_k=64)
