"""Deployment/predict surface (mxnet_tpu/predict.py).

Reference parity target: the standalone predict API
(src/c_api/c_predict_api.cc:1-334) — build from serialized artifacts,
run inference without the training stack. Gates: (a) Predictor output
== Module.predict bitwise-close, (b) the artifact loads and runs in a
FRESH subprocess that never constructs a Symbol or Module, (c) shape
mismatches error per the fixed-shape contract.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.models import lenet


def _trained_module(batch=8):
    net = lenet.get_symbol(num_classes=4)
    it = mx.io.NDArrayIter(
        np.random.rand(32, 1, 28, 28).astype(np.float32),
        (np.random.rand(32) * 4).astype(np.float32), batch)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=1, initializer=mx.initializer.Xavier(),
            optimizer_params={"learning_rate": 0.01})
    return net, mod


def test_export_roundtrip_matches_module_predict(tmp_path):
    net, mod = _trained_module()
    arg_params, aux_params = mod.get_params()
    path = str(tmp_path / "lenet.mxp")
    mx.export_model(path, net, arg_params, aux_params,
                    {"data": (8, 1, 28, 28)})

    x = np.random.rand(8, 1, 28, 28).astype(np.float32)
    it = mx.io.NDArrayIter(x, None, 8)
    expect = mod.predict(it).asnumpy()

    pred = mx.Predictor(path)
    assert pred.output_names == net.list_outputs()
    got = pred.forward(data=x)[0].asnumpy()
    np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)
    # get_output mirrors MXPredGetOutput
    np.testing.assert_allclose(pred.get_output(0).asnumpy(), got)


def test_predictor_runs_in_fresh_process(tmp_path):
    """The artifact must be servable by a process that never builds a
    Symbol/Module (the reference's deployment story: amalgamated predict
    lib + params blob)."""
    net, mod = _trained_module()
    arg_params, aux_params = mod.get_params()
    path = str(tmp_path / "lenet.mxp")
    mx.export_model(path, net, arg_params, aux_params,
                    {"data": (8, 1, 28, 28)})
    x = np.random.rand(8, 1, 28, 28).astype(np.float32)
    np.save(str(tmp_path / "x.npy"), x)
    it = mx.io.NDArrayIter(x, None, 8)
    expect = mod.predict(it).asnumpy()
    np.save(str(tmp_path / "expect.npy"), expect)

    script = f"""
import jax
jax.config.update("jax_platforms", "cpu")   # site hook may pin a TPU
import numpy as np
from mxnet_tpu.predict import Predictor
import mxnet_tpu.symbol as _sym_mod
import mxnet_tpu.module as _mod_mod
# prove the loader path itself never constructs graph objects
_sym_mod.Symbol.__init__ = lambda *a, **k: (_ for _ in ()).throw(
    RuntimeError("Symbol constructed in predictor process"))
p = Predictor({str(tmp_path / 'lenet.mxp')!r})
x = np.load({str(tmp_path / 'x.npy')!r})
out = p.forward(data=x)[0].asnumpy()
expect = np.load({str(tmp_path / 'expect.npy')!r})
np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-6)
print("PREDICTOR_SUBPROCESS_OK")
"""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run([sys.executable, "-c", script],
                       capture_output=True, text=True, env=env,
                       cwd=os.path.dirname(os.path.dirname(
                           os.path.abspath(__file__))))
    assert "PREDICTOR_SUBPROCESS_OK" in r.stdout, r.stderr[-2000:]


def test_predictor_rejects_wrong_shape(tmp_path):
    net, mod = _trained_module()
    arg_params, aux_params = mod.get_params()
    path = str(tmp_path / "lenet.mxp")
    mx.export_model(path, net, arg_params, aux_params,
                    {"data": (8, 1, 28, 28)})
    pred = mx.Predictor(path)
    with pytest.raises(mx.base.MXNetError):
        pred.forward(data=np.zeros((4, 1, 28, 28), np.float32))


@pytest.mark.slow
def test_export_resnet50(tmp_path):
    """Flagship round-trip (VERDICT r3 #4: 'export ResNet-50, reload,
    outputs match Module.predict') at a reduced image size so the CPU
    trace stays test-sized."""
    from mxnet_tpu.models import resnet
    net = resnet.get_symbol(num_classes=10, num_layers=50,
                            image_shape="3,32,32")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind([("data", (4, 3, 32, 32))], [("softmax_label", (4,))],
             for_training=False)
    mod.init_params(mx.initializer.Xavier())
    arg_params, aux_params = mod.get_params()
    path = str(tmp_path / "resnet50.mxp")
    mx.export_model(path, net, arg_params, aux_params,
                    {"data": (4, 3, 32, 32)})
    x = np.random.rand(4, 3, 32, 32).astype(np.float32)
    it = mx.io.NDArrayIter(x, None, 4)
    expect = mod.predict(it).asnumpy()
    got = mx.Predictor(path).forward(data=x)[0].asnumpy()
    np.testing.assert_allclose(got, expect, rtol=1e-4, atol=1e-5)


def test_manifest_records_input_dtypes_int_roundtrip(tmp_path):
    """Satellite (ISSUE 8): the manifest records each input's dtype and
    ``Predictor.forward`` respects it instead of hard-coding float32 —
    an int32 embedding-id input must round-trip through the artifact."""
    ids_sym = mx.sym.var("data")
    emb = mx.sym.Embedding(ids_sym, input_dim=10, output_dim=4,
                           name="embed")
    weight = np.random.RandomState(0).rand(10, 4).astype(np.float32)
    path = str(tmp_path / "embed.mxp")
    mx.export_model(path, emb, {"embed_weight": weight}, {},
                    {"data": (3, 5)}, data_dtypes={"data": np.int32})

    pred = mx.Predictor(path)
    assert pred.input_dtypes == {"data": np.dtype(np.int32)}
    ids = np.random.RandomState(1).randint(0, 10, (3, 5))
    out = pred.forward(data=ids)[0].asnumpy()
    np.testing.assert_allclose(out, weight[ids], rtol=1e-6)
    # a float array of ids still works (cast to the recorded dtype)
    out2 = pred.forward(data=ids.astype(np.float64))[0].asnumpy()
    np.testing.assert_allclose(out2, out)


def test_manifest_bf16_input_dtype(tmp_path):
    """bf16-exported inputs: the program's avals are bf16, so the old
    float32 coercion would be rejected at call time; the recorded-dtype
    cast must make float32 host arrays servable."""
    import jax.numpy as jnp
    net, mod = _trained_module()
    arg_params, aux_params = mod.get_params()
    path = str(tmp_path / "lenet_bf16.mxp")
    mx.export_model(path, net, arg_params, aux_params,
                    {"data": (8, 1, 28, 28)},
                    data_dtypes={"data": jnp.bfloat16})
    pred = mx.Predictor(path)
    assert pred.input_dtypes["data"] == np.dtype(jnp.bfloat16)

    x = np.random.rand(8, 1, 28, 28).astype(np.float32)
    got = pred.forward(data=x)[0].asnumpy()
    it = mx.io.NDArrayIter(x, None, 8)
    expect = mod.predict(it).asnumpy()
    # bf16 input quantization: close, not bitwise
    np.testing.assert_allclose(got, expect, rtol=0.1, atol=0.05)


def test_predictor_batch_forward_dynamic_rows(tmp_path):
    """Satellite (ISSUE 8): ``batch_forward`` takes a dynamic leading
    batch dim, windows it through the fixed exported batch with the
    serving pad/slice helpers, and matches Module.predict."""
    net, mod = _trained_module(batch=4)
    arg_params, aux_params = mod.get_params()
    path = str(tmp_path / "lenet_b4.mxp")
    mx.export_model(path, net, arg_params, aux_params,
                    {"data": (4, 1, 28, 28)})
    pred = mx.Predictor(path)

    x = np.random.rand(10, 1, 28, 28).astype(np.float32)
    got = pred.batch_forward(data=x)[0].asnumpy()
    assert got.shape[0] == 10
    expect = mod.predict(mx.io.NDArrayIter(x, None, 4)).asnumpy()
    np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)
    # full-window rows are the exported program's output verbatim
    direct = pred.forward(data=x[:4])[0].asnumpy()
    assert np.array_equal(got[:4], direct)
    # fewer rows than the exported batch also work (one padded window)
    small = pred.batch_forward(data=x[:2])[0].asnumpy()
    np.testing.assert_allclose(small, expect[:2], rtol=1e-5, atol=1e-6)
