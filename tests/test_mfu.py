"""MFU/roofline accounting: cost metadata, coverage, reporting surfaces.

Covers telemetry/mfu.py's cost-table fold, the roofline classifier, the
registry gauges the fit loop records, the MF601 coverage lint rule, the
mxlint --mfu-audit surface, and tools/diagnose.py's roofline section.
"""
import json
import os
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.analysis import lint_symbol
from mxnet_tpu.ops import cost as cost_mod
from mxnet_tpu.ops.registry import OP_REGISTRY, register
from mxnet_tpu.telemetry import metrics, mfu

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")


def _mlp():
    d = mx.sym.var("data")
    h = mx.sym.FullyConnected(d, num_hidden=16, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(h, name="softmax")


# ------------------------------------------------------------ cost table
def test_resnet20_cost_table_full_coverage():
    from mxnet_tpu import models
    sym = models.resnet.get_symbol(10, 20, "3,32,32")
    t = mfu.cost_table(sym, {"data": (4, 3, 32, 32),
                             "softmax_label": (4,)})
    assert t["uncovered"] == []
    assert t["covered_nodes"] == t["compute_nodes"]
    assert t["flops"] > 1e8                      # ~3.4e8 fwd at batch 4
    assert t["train_flops"] > t["flops"]
    conv = t["per_op"]["Convolution"]
    assert conv["flops"] / t["flops"] > 0.9      # conv-dominated


def test_fc_flops_exact():
    sym = _mlp()
    t = mfu.cost_table(sym, {"data": (8, 32), "softmax_label": (8,)})
    # fc1: 2*8*32*16 + 8*16 bias; fc2: 2*8*16*4 + 8*4
    expect = (2 * 8 * 32 * 16 + 8 * 16) + (2 * 8 * 16 * 4 + 8 * 4)
    assert t["per_op"]["FullyConnected"]["flops"] == expect


def test_roofline_classification():
    from mxnet_tpu import models
    sym = models.resnet.get_symbol(10, 20, "3,32,32")
    t = mfu.cost_table(sym, {"data": (4, 3, 32, 32),
                             "softmax_label": (4,)})
    peak, bw = mfu.device_peaks("TPU v5e")
    rows = mfu.roofline(t, peak, bw)
    assert rows[0]["op"] == "Convolution"        # biggest share first
    for r in rows:
        assert r["bound"] in ("compute", "memory")
        assert 0 <= r.get("attainable_frac", 0) <= 1
        assert r["ai"] >= 0
    assert abs(sum(r["share"] for r in rows) - 1.0) < 1e-6
    # no peaks known (CPU): rows still classify, no attainable_frac
    rows_cpu = mfu.roofline(t)
    assert all("attainable_frac" not in r for r in rows_cpu)


def test_model_mfu_math():
    assert mfu.model_mfu(1e12, 0.01, 1e14) == pytest.approx(1.0)
    assert mfu.model_mfu(1e12, 0.01, None) is None
    assert mfu.model_mfu(None, 0.01, 1e14) is None


def test_device_peaks_table():
    peak, bw = mfu.device_peaks("TPU v5e")
    assert peak == 197e12 and bw == 819e9
    assert mfu.device_peaks("TPU v4", dtype="f32")[0] == 137e12
    assert mfu.device_peaks("Colossus") == (None, None)


def test_record_gauges():
    metrics.reset()
    sym = _mlp()
    t = mfu.cost_table(sym, {"data": (8, 32), "softmax_label": (8,)})
    mfu.record_gauges(t, step_seconds=0.01, peak_flops=1e12)
    g = metrics.get_metric("mfu.op.flops", op="FullyConnected")
    assert g is not None and g.value > 0
    assert metrics.get_metric("mfu.node_coverage").value == 1.0
    assert metrics.get_metric("mfu.model").value > 0
    assert metrics.get_metric("mfu.achieved_flops_per_sec").value > 0


def test_executor_cost_table():
    sym = _mlp()
    exe = sym.simple_bind(mx.cpu(), data=(8, 32))
    t = exe.cost_table()
    assert t is not None and t["flops"] > 0


# ------------------------------------------------- fit-loop MFU gauges
def test_fit_records_mfu_gauges():
    mx.telemetry.enable()
    try:
        metrics.reset()
        rng = np.random.RandomState(0)
        X = rng.rand(16, 32).astype(np.float32)
        Y = (rng.rand(16) * 4).astype(np.float32)
        it = mx.io.NDArrayIter(X, Y, batch_size=8,
                               label_name="softmax_label")
        mod = mx.mod.Module(_mlp(), context=mx.cpu())
        mod.fit(it, num_epoch=1, initializer=mx.initializer.Uniform(0.1),
                optimizer_params={"learning_rate": 0.1})
        ach = metrics.get_metric("mfu.achieved_flops_per_sec")
        assert ach is not None and ach.value > 0
        cov = metrics.get_metric("mfu.node_coverage")
        assert cov is not None and cov.value == 1.0
        # no peak on the CPU backend: the MFU-of-peak gauge stays unset
        assert metrics.get_metric("mfu.model") is None
    finally:
        mx.telemetry.disable()
        metrics.reset()


# --------------------------------------------------- MF601 + mxlint
def test_mf601_fires_for_uncovered_op():
    if "_nocost_probe" not in OP_REGISTRY:
        register("_nocost_probe", inputs=("data",),
                 simple=lambda attrs, x: x,
                 infer_shape=lambda attrs, s, out_known=None:
                 (s, [s[0]], []))
        mx.sym._init_symbol_module(mx.sym.__dict__)
    net = mx.sym._nocost_probe(mx.sym.var("data"))
    report = lint_symbol(net, shapes={"data": (2, 4)})
    assert "MF601" in report.rules
    assert any(d.op == "_nocost_probe" for d in report)


def test_bundled_models_mf601_clean():
    """The flagship-model op set is fully seeded — MF601 stays quiet
    over the zoo (the zero-false-positive gate for the new rule)."""
    from mxnet_tpu import models
    sym = models.inception_bn.get_symbol(10)
    report = lint_symbol(sym, shapes={"data": (1, 3, 224, 224)})
    assert "MF601" not in report.rules


def test_mxlint_mfu_audit(capsys):
    sys.path.insert(0, TOOLS)
    try:
        import mxlint
        rc = mxlint.main(["--mfu-audit"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "missing cost metadata" in out
        # covered ops never appear as MF601 coverage gaps (they DO now
        # appear in the planner's per-op byte table below the list)
        assert "MF601 [info] op 'Convolution'" not in out
        assert "planner per-op" in out
    finally:
        sys.path.remove(TOOLS)


def test_optimizer_flops_helper():
    assert cost_mod.optimizer_flops("sgd_mom", 100) == 600.0
    assert cost_mod.optimizer_flops("adam", 10) == 120.0
    assert cost_mod.optimizer_flops("unknown_opt", 10) == 60.0


# ------------------------------------------------------- diagnose render
def test_diagnose_renders_roofline(tmp_path):
    sys.path.insert(0, TOOLS)
    try:
        import diagnose
        lines = [
            json.dumps({"type": "gauge", "name": "mfu.op.flops",
                        "labels": {"op": "Convolution"}, "value": 9e9}),
            json.dumps({"type": "gauge", "name": "mfu.op.ai",
                        "labels": {"op": "Convolution"}, "value": 180.0}),
            json.dumps({"type": "gauge", "name": "mfu.op.flops",
                        "labels": {"op": "BatchNorm"}, "value": 1e9}),
            json.dumps({"type": "gauge", "name": "mfu.op.ai",
                        "labels": {"op": "BatchNorm"}, "value": 1.2}),
            json.dumps({"type": "gauge", "name": "mfu.model",
                        "labels": {}, "value": 0.41}),
            json.dumps({"type": "gauge",
                        "name": "mfu.achieved_flops_per_sec",
                        "labels": {}, "value": 8.1e13}),
            json.dumps({"type": "gauge", "name": "mfu.node_coverage",
                        "labels": {}, "value": 0.97}),
        ]
        text = diagnose.render_jsonl(lines)
        assert "roofline / MFU:" in text
        assert "model MFU 41.0% of peak" in text
        assert "coverage: 97%" in text
        assert "Convolution" in text and "compute-bound" in text
        assert "BatchNorm" in text and "memory-bound" in text

        # crash-report path renders the same section from the metrics
        # snapshot
        crash = {
            "type": "crash_report", "time": "t", "pid": 1,
            "where": "executor.forward",
            "metrics": {"counters": {}, "gauges": {
                'mfu.op.flops{op="Convolution"}': 9e9,
                'mfu.op.ai{op="Convolution"}': 180.0,
                "mfu.node_coverage": 0.5,
            }},
            "ring": [],
        }
        text2 = diagnose.render_crash(crash)
        assert "roofline / MFU:" in text2
        assert "LOW" in text2                    # coverage warning
    finally:
        sys.path.remove(TOOLS)
