"""NDArray tests (mirrors reference tests/python/unittest/test_ndarray.py)."""
import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal, same


def test_ndarray_creation():
    a = mx.nd.zeros((3, 4))
    assert a.shape == (3, 4)
    assert a.dtype == np.float32
    assert a.asnumpy().sum() == 0
    b = mx.nd.ones((2, 2), dtype=np.int32)
    assert b.dtype == np.int32
    assert b.asnumpy().sum() == 4
    c = mx.nd.full((2, 3), 7.5)
    assert c.asnumpy().max() == 7.5
    d = mx.nd.array([[1, 2], [3, 4]])
    assert same(d.asnumpy(), np.array([[1, 2], [3, 4]], dtype=np.float32))


def test_ndarray_elementwise():
    np.random.seed(0)
    for _ in range(3):
        shape = tuple(np.random.randint(1, 8, size=2))
        a_np = np.random.rand(*shape).astype(np.float32)
        b_np = np.random.rand(*shape).astype(np.float32) + 0.1
        a, b = mx.nd.array(a_np), mx.nd.array(b_np)
        assert_almost_equal(a + b, a_np + b_np)
        assert_almost_equal(a - b, a_np - b_np)
        assert_almost_equal(a * b, a_np * b_np)
        assert_almost_equal(a / b, a_np / b_np, rtol=1e-5)
        assert_almost_equal(a + 2, a_np + 2)
        assert_almost_equal(2 - a, 2 - a_np)
        assert_almost_equal(a ** 2, a_np ** 2, rtol=1e-5)
        assert_almost_equal(-a, -a_np)


def test_ndarray_inplace():
    a = mx.nd.ones((2, 3))
    alias = a
    a += 1
    assert alias.asnumpy().sum() == 12  # alias sees the mutation
    a *= 3
    assert_almost_equal(alias, np.full((2, 3), 6, dtype=np.float32))


def test_ndarray_setitem():
    a = mx.nd.zeros((3, 4))
    a[:] = 2
    assert a.asnumpy().sum() == 24
    a[1] = 5
    assert a.asnumpy()[1].sum() == 20
    a[0:2] = 1
    assert a.asnumpy()[0:2].sum() == 8
    b = mx.nd.zeros((3,))
    b[1] = 3.0
    assert same(b.asnumpy(), np.array([0, 3, 0], dtype=np.float32))


def test_ndarray_slicing():
    a_np = np.arange(24).reshape(4, 6).astype(np.float32)
    a = mx.nd.array(a_np)
    assert same(a[1].asnumpy(), a_np[1])
    assert same(a[1:3].asnumpy(), a_np[1:3])
    assert same(a.T.asnumpy(), a_np.T)


def test_ndarray_reshape():
    a = mx.nd.array(np.arange(12).astype(np.float32))
    b = a.reshape((3, 4))
    assert b.shape == (3, 4)
    c = b.reshape((-1, 2))
    assert c.shape == (6, 2)
    d = b.reshape((0, 2, 2))
    assert d.shape == (3, 2, 2)


def test_ndarray_copy():
    a = mx.nd.array(np.random.rand(3, 3))
    b = a.copy()
    b += 1
    assert not same(a.asnumpy(), b.asnumpy())
    c = mx.nd.zeros((3, 3))
    a.copyto(c)
    assert same(a.asnumpy(), c.asnumpy())


def test_ndarray_astype():
    a = mx.nd.array([1.5, 2.5])
    b = a.astype(np.int32)
    assert b.dtype == np.int32
    assert same(b.asnumpy(), np.array([1, 2], dtype=np.int32))


def test_ndarray_saveload():
    arrays = {"w": mx.nd.array(np.random.rand(3, 4)),
              "b": mx.nd.array(np.random.rand(7))}
    with tempfile.TemporaryDirectory() as d:
        fname = os.path.join(d, "test.params")
        mx.nd.save(fname, arrays)
        loaded = mx.nd.load(fname)
        assert set(loaded) == {"w", "b"}
        for k in arrays:
            assert_almost_equal(arrays[k], loaded[k])
        # list form
        mx.nd.save(fname, list(arrays.values()))
        llist = mx.nd.load(fname)
        assert isinstance(llist, list) and len(llist) == 2


def test_ndarray_registry_ops():
    a_np = np.random.rand(3, 4).astype(np.float32)
    a = mx.nd.array(a_np)
    assert_almost_equal(mx.nd.exp(a), np.exp(a_np), rtol=1e-5)
    assert_almost_equal(mx.nd.sqrt(a), np.sqrt(a_np), rtol=1e-5)
    assert_almost_equal(mx.nd.square(a), a_np ** 2, rtol=1e-5)
    assert_almost_equal(mx.nd.sum(a), a_np.sum(), rtol=1e-5)
    assert_almost_equal(mx.nd.sum(a, axis=1), a_np.sum(axis=1), rtol=1e-5)
    assert_almost_equal(mx.nd.transpose(a), a_np.T)
    assert_almost_equal(mx.nd.dot(a, mx.nd.array(a_np.T)),
                        a_np.dot(a_np.T), rtol=1e-4)
    assert_almost_equal(mx.nd.clip(a, a_min=0.2, a_max=0.8),
                        np.clip(a_np, 0.2, 0.8))


def test_ndarray_broadcast():
    a = mx.nd.array(np.random.rand(3, 1).astype(np.float32))
    b = mx.nd.array(np.random.rand(1, 4).astype(np.float32))
    out = mx.nd.broadcast_add(a, b)
    assert out.shape == (3, 4)
    assert_almost_equal(out, a.asnumpy() + b.asnumpy())
    c = mx.nd.broadcast_to(a, shape=(3, 5))
    assert c.shape == (3, 5)


def test_ndarray_concat_onehot_take():
    a = mx.nd.array(np.arange(6).reshape(2, 3).astype(np.float32))
    b = mx.nd.array(np.arange(6, 12).reshape(2, 3).astype(np.float32))
    c = mx.nd.concatenate([a, b], axis=0)
    assert c.shape == (4, 3)
    idx = mx.nd.array([0, 2])
    oh = mx.nd.one_hot(idx, depth=4)
    assert same(oh.asnumpy(), np.eye(4, dtype=np.float32)[[0, 2]])
    taken = mx.nd.take(a, mx.nd.array([1, 0]))
    assert same(taken.asnumpy(), a.asnumpy()[[1, 0]])


def test_ndarray_sort_topk():
    a_np = np.random.rand(4, 5).astype(np.float32)
    a = mx.nd.array(a_np)
    assert_almost_equal(mx.nd.sort(a), np.sort(a_np, axis=-1))
    top = mx.nd.topk(a, k=2, ret_typ="value")
    expect = np.sort(a_np, axis=-1)[:, ::-1][:, :2]
    assert_almost_equal(top, expect)


def test_ndarray_wait_sync():
    a = mx.nd.ones((100, 100))
    b = a * 2
    b.wait_to_read()
    mx.nd.waitall()
    assert b.asnumpy().sum() == 20000


def test_ndarray_scalar_ops():
    a = mx.nd.array([2.0])
    assert float(a.asscalar()) == 2.0
    assert bool(mx.nd.array([1.0]))
    assert len(mx.nd.zeros((5, 2))) == 5


def test_module_level_arithmetic_helpers():
    """reference ndarray.py module helpers: scalar-or-array dispatch,
    comparisons returning 0/1 floats."""
    a = mx.nd.array([[1.0, 5.0], [3.0, 2.0]])
    b = mx.nd.array([[4.0, 1.0], [3.0, 6.0]])
    np.testing.assert_allclose(mx.nd.add(a, 1.0).asnumpy(),
                               a.asnumpy() + 1)
    np.testing.assert_allclose(mx.nd.maximum(a, b).asnumpy(),
                               np.maximum(a.asnumpy(), b.asnumpy()))
    np.testing.assert_allclose(mx.nd.minimum(a, 3.0).asnumpy(),
                               np.minimum(a.asnumpy(), 3.0))
    np.testing.assert_allclose(mx.nd.power(2.0, a).asnumpy(),
                               2.0 ** a.asnumpy())
    eq = mx.nd.equal(a, b).asnumpy()
    assert eq.dtype == np.float32
    np.testing.assert_allclose(
        eq, (a.asnumpy() == b.asnumpy()).astype(np.float32))
    np.testing.assert_allclose(
        mx.nd.lesser_equal(a, b).asnumpy(),
        (a.asnumpy() <= b.asnumpy()).astype(np.float32))
    mv = mx.nd.moveaxis(mx.nd.array(np.zeros((2, 3, 4))), 0, 2)
    assert mv.shape == (3, 4, 2)


def test_onehot_encode_and_sym_helpers():
    idx = mx.nd.array([0.0, 2.0, 1.0])
    out = mx.nd.zeros((3, 4))
    res = mx.nd.onehot_encode(idx, out)
    expect = np.zeros((3, 4), np.float32)
    expect[[0, 1, 2], [0, 2, 1]] = 1
    np.testing.assert_allclose(res.asnumpy(), expect)
    # symbol-level pow/maximum/minimum/hypot over Symbol/scalar mixes
    import mxnet_tpu.symbol as S
    x = mx.sym.var("x")
    exe = S.pow(x, 2.0).simple_bind(mx.cpu(), x=(2,), grad_req="null")
    exe.arg_dict["x"][:] = np.array([3.0, 4.0], np.float32)
    np.testing.assert_allclose(exe.forward()[0].asnumpy(), [9.0, 16.0])
    exe = S.hypot(x, 4.0).simple_bind(mx.cpu(), x=(1,), grad_req="null")
    exe.arg_dict["x"][:] = np.array([3.0], np.float32)
    np.testing.assert_allclose(exe.forward()[0].asnumpy(), [5.0])
    exe = S.maximum(2.0, x).simple_bind(mx.cpu(), x=(2,), grad_req="null")
    exe.arg_dict["x"][:] = np.array([1.0, 7.0], np.float32)
    np.testing.assert_allclose(exe.forward()[0].asnumpy(), [2.0, 7.0])
    assert S.pow(2.0, 3.0) == 8.0


def test_nd_imdecode():
    import io as _io
    sys_path = __import__("sys").path
    sys_path.insert(0, "tools")
    import im2rec
    img = (np.arange(24 * 32 * 3, dtype=np.uint8) % 255).reshape(24, 32, 3)
    buf = im2rec._encode(img, quality=95)
    dec = mx.nd.imdecode(bytes(buf))
    assert dec.shape == (24, 32, 3)
    # batched out + index slot
    out = mx.nd.zeros((2, 24, 32, 3))
    mx.nd.imdecode(bytes(buf), out=out, index=1)
    host = out.asnumpy()
    assert host[0].sum() == 0 and host[1].sum() > 0
    np.testing.assert_allclose(host[1], dec.asnumpy())
    # clip_rect
    clipped = mx.nd.imdecode(bytes(buf), clip_rect=(4, 2, 20, 14))
    assert clipped.shape == (12, 16, 3)
