"""Optimizer tests (mirrors reference test_optimizer.py — python reference
implementation vs fused-op consistency)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal


def _run_steps(opt, w0, grads, index=0):
    w = mx.nd.array(w0.copy())
    state = opt.create_state(index, w)
    for g in grads:
        opt.update(index, w, mx.nd.array(g), state)
    return w.asnumpy()


def test_sgd_matches_numpy():
    rng = np.random.RandomState(0)
    w0 = rng.randn(10).astype(np.float32)
    grads = [rng.randn(10).astype(np.float32) for _ in range(5)]
    opt = mx.optimizer.SGD(learning_rate=0.1, wd=0.01, rescale_grad=0.5)
    out = _run_steps(opt, w0, grads)
    # numpy reference
    w = w0.copy()
    for g in grads:
        gg = 0.5 * g + 0.01 * w
        w = w - 0.1 * gg
    assert_almost_equal(out, w, rtol=1e-5)


def test_sgd_momentum_matches_numpy():
    rng = np.random.RandomState(1)
    w0 = rng.randn(8).astype(np.float32)
    grads = [rng.randn(8).astype(np.float32) for _ in range(5)]
    opt = mx.optimizer.SGD(learning_rate=0.2, momentum=0.9)
    out = _run_steps(opt, w0, grads)
    w = w0.copy()
    mom = np.zeros_like(w)
    for g in grads:
        mom = 0.9 * mom - 0.2 * g
        w = w + mom
    assert_almost_equal(out, w, rtol=1e-5)


def test_adam_matches_numpy():
    rng = np.random.RandomState(2)
    w0 = rng.randn(6).astype(np.float32)
    grads = [rng.randn(6).astype(np.float32) for _ in range(4)]
    opt = mx.optimizer.Adam(learning_rate=0.01)
    out = _run_steps(opt, w0, grads)
    w = w0.astype(np.float64).copy()
    m = np.zeros_like(w)
    v = np.zeros_like(w)
    b1, b2, eps = 0.9, 0.999, 1e-8
    for t, g in enumerate(grads, 1):
        lr = 0.01 * np.sqrt(1 - b2 ** t) / (1 - b1 ** t)
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        w = w - lr * m / (np.sqrt(v) + eps)
    assert_almost_equal(out, w.astype(np.float32), rtol=1e-4)


def test_rmsprop_runs():
    rng = np.random.RandomState(3)
    w0 = rng.randn(6).astype(np.float32)
    grads = [rng.randn(6).astype(np.float32) for _ in range(4)]
    out = _run_steps(mx.optimizer.RMSProp(learning_rate=0.01), w0, grads)
    assert not np.allclose(out, w0)
    out_c = _run_steps(mx.optimizer.RMSProp(learning_rate=0.01,
                                            centered=True), w0, grads)
    assert not np.allclose(out_c, w0)


def test_adagrad_adadelta_ftrl_nag():
    rng = np.random.RandomState(4)
    w0 = rng.randn(6).astype(np.float32)
    grads = [rng.randn(6).astype(np.float32) for _ in range(4)]
    for opt in [mx.optimizer.AdaGrad(learning_rate=0.1),
                mx.optimizer.AdaDelta(),
                mx.optimizer.Ftrl(),
                mx.optimizer.NAG(learning_rate=0.1, momentum=0.9),
                mx.optimizer.SGLD(learning_rate=0.01),
                mx.optimizer.DCASGD(learning_rate=0.1, momentum=0.9)]:
        out = _run_steps(opt, w0, grads)
        assert out.shape == w0.shape
        assert np.isfinite(out).all()
        assert not np.allclose(out, w0), type(opt).__name__


def test_clip_gradient():
    w0 = np.zeros(3, dtype=np.float32)
    grads = [np.array([100.0, -100.0, 0.1], dtype=np.float32)]
    opt = mx.optimizer.SGD(learning_rate=1.0, clip_gradient=1.0)
    out = _run_steps(opt, w0, grads)
    assert_almost_equal(out, np.array([-1.0, 1.0, -0.1]), rtol=1e-5)


def test_lr_scheduler():
    sched = mx.lr_scheduler.FactorScheduler(step=10, factor=0.5)
    opt = mx.optimizer.SGD(learning_rate=1.0, lr_scheduler=sched)
    w = mx.nd.array(np.zeros(1, dtype=np.float32))
    state = opt.create_state(0, w)
    for _ in range(25):
        opt.update(0, w, mx.nd.array(np.ones(1, dtype=np.float32)), state)
    # after 25 updates with step=10 the rate has decayed twice
    assert sched(25) == 0.25
    assert sched(5) == 1.0  # stateless: earlier queries still exact
    multi = mx.lr_scheduler.MultiFactorScheduler(step=[5, 15], factor=0.1)
    multi.base_lr = 1.0
    assert multi(20) < 1.0


def test_lr_wd_mult_from_symbol():
    data = mx.sym.var("data")
    w = mx.sym.var("fc_weight", lr_mult=0.0)
    fc = mx.sym.FullyConnected(data, weight=w, num_hidden=2, name="fc")
    out = mx.sym.SoftmaxOutput(fc, name="softmax")
    opt = mx.optimizer.SGD(learning_rate=1.0, sym=out,
                           param_idx2name={0: "fc_weight", 1: "fc_bias"})
    assert opt._get_lr(0) == 0.0  # lr_mult from symbol attr kills updates
    assert opt._get_lr(1) == 1.0


def test_get_updater():
    opt = mx.optimizer.SGD(learning_rate=0.1)
    updater = mx.optimizer.get_updater(opt)
    w = mx.nd.ones((4,))
    g = mx.nd.ones((4,))
    updater(0, g, w)
    assert_almost_equal(w, np.full(4, 0.9), rtol=1e-6)


def test_create_by_name():
    opt = mx.optimizer.create("adam", learning_rate=0.1)
    assert isinstance(opt, mx.optimizer.Adam)
    opt2 = mx.optimizer.create("ccsgd", learning_rate=0.1)
    assert isinstance(opt2, mx.optimizer.ccSGD)
