"""Multi-process dist_sync: the launcher + the nightly arithmetic gate.

Mirrors the reference's `tools/launch.py -n 4 python dist_sync_kvstore.py`
(reference: tests/nightly/test_all.sh:36) — multi-node simulated by
multi-process on one host, real collectives between the processes.
"""
import os
import signal
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _launch(nworkers, script="dist_sync_worker.py", timeout=600,
            local_devices=None):
    env = dict(os.environ)
    env.pop("DMLC_NUM_WORKER", None)  # never inherit stale cluster env
    env.pop("DMLC_WORKER_ID", None)
    if local_devices:
        # give every worker process its own multi-device view — the
        # dist_device_sync topology (N hosts x L chips each)
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "").replace(
                "--xla_force_host_platform_device_count=8", "").strip()
            + f" --xla_force_host_platform_device_count={local_devices}"
        ).strip()
    # own session so a timeout can kill the whole tree: worker
    # grandchildren inherit the stdout pipe, and killing only the
    # launcher would leave communicate() blocked on the open write ends
    proc = subprocess.Popen(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
         "-n", str(nworkers), sys.executable,
         os.path.join(ROOT, "tests", script)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=ROOT, start_new_session=True)
    try:
        stdout, stderr = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        stdout, stderr = proc.communicate()
        raise AssertionError(
            f"distributed job wedged past {timeout}s; tail:\n"
            f"{stdout[-1500:]}\n{stderr[-1500:]}")
    return subprocess.CompletedProcess(proc.args, proc.returncode,
                                       stdout, stderr)


@pytest.mark.parametrize("nworkers", [2, 4])
def test_dist_sync_invariant_multiprocess(nworkers):
    res = _launch(nworkers)
    assert res.returncode == 0, (res.stdout[-2000:], res.stderr[-2000:])
    # workers share the stdout pipe, so lines can interleave — count
    # whole-marker occurrences, not line prefixes
    assert res.stdout.count("DIST_SYNC_OK") == nworkers, (
        res.stdout[-2000:], res.stderr[-2000:])
    for rank in range(nworkers):
        assert f"rank={rank} nworker={nworkers}" in res.stdout


def test_dist_sync_invariant_multidevice():
    """2 processes x 4 local devices: the kvstore reduction must ride a
    (proc, dev) mesh — every local device reduces a slice of the buffer
    (the reference dist_device_sync topology, comm.h:289-361) — and
    still satisfy the same nightly arithmetic invariant."""
    res = _launch(2, local_devices=4)
    assert res.returncode == 0, (res.stdout[-2000:], res.stderr[-2000:])
    assert res.stdout.count("DIST_SYNC_OK") == 2, (
        res.stdout[-2000:], res.stderr[-2000:])


def test_dead_worker_detected():
    """Failure detection (SURVEY §5.3): kill one worker mid-job; every
    survivor's get_num_dead_node() must go positive (reference:
    kvstore_dist.h GetDeadNodes over ps-lite heartbeats). Workers are
    spawned directly (launch.py would tear the job down on the planned
    death — right for real jobs, wrong for this gate)."""
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    n = 3
    procs = []
    for rank in range(n):
        env = dict(os.environ)
        env.update({"DMLC_ROLE": "worker", "DMLC_NUM_WORKER": str(n),
                    "DMLC_WORKER_ID": str(rank),
                    "DMLC_PS_ROOT_URI": "127.0.0.1",
                    "DMLC_PS_ROOT_PORT": str(port),
                    "PS_HEARTBEAT_TIMEOUT": "5"})
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(ROOT, "tests",
                                          "dead_node_worker.py")],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env, cwd=ROOT))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    stdout = "\n".join(outs)
    markers = [ln for ln in stdout.splitlines() if "DEAD_NODE_SEEN" in ln]
    assert len(markers) == n - 1, stdout[-2000:]
    for ln in markers:
        assert "dead=0" not in ln, markers
    # survivors exit 0 only when detection succeeded (worker contract)
    assert [p.returncode for p in procs[:-1]] == [0] * (n - 1)
    assert procs[-1].returncode == 17


@pytest.mark.parametrize("nworkers,local_devices", [(2, None), (4, None),
                                                    (2, 4)])
def test_dist_fit_lockstep(nworkers, local_devices):
    """Module.fit over dist_sync (the dist_lenet analog): every worker
    learns AND ends with bit-identical parameters. The (2, 4) case is the
    pod-host topology — 2 processes x 4 local devices each — proving the
    (proc, dev) kvstore mesh works end-to-end through the updater path,
    not just the raw push/pull invariant."""
    res = _launch(nworkers, script="dist_fit_worker.py",
                  local_devices=local_devices)
    assert res.returncode == 0, (res.stdout[-1500:], res.stderr[-1500:])
    assert res.stdout.count("DIST_FIT_OK") == nworkers, res.stdout[-1500:]
    digests = {tok for tok in res.stdout.split()
               if tok.startswith("params=")}
    assert len(digests) == 1, f"replicas diverged: {digests}"
