"""Transformer workload subsystem (ISSUE 13, ROADMAP 1).

Pins the tentpole end to end: the decoder-only LM trains through
``Module.fit(spmd=True)`` on a (data x seq) virtual-device mesh with
params matching the single-device unsharded run to float ulps at K=1
and K=4; the ``attention`` OpDef carries three gated lowerings (xla
composition / Pallas flash / sequence-sharded ring) selected by the
kernel tier + plan; and N incremental KV-cache decode steps reproduce
the length-N full-sequence forward (f32 and bf16), export through
``export_model`` as a stateful artifact, and serve through ``serve()``
with zero steady-state compiles. Satellites ride along: ring-attention
fwd/grad parity vs the full attention (the PR-0 dead code resurrected),
cost-table coverage, KV-cache bytes in the memory planner, and the
zero-false-positive lint gates (zoo membership is pinned in
tools/mxlint's corpus; the precision/memplan/SH6xx surfaces here).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu.models import transformer as tfm
from mxnet_tpu.parallel import MeshConfig
from mxnet_tpu.parallel import spmd as spmd_mod
from mxnet_tpu.parallel.spmd import SpmdPlan
from mxnet_tpu.parallel.ring_attention import (attention as full_attention,
                                               ring_attention_sharded)
from mxnet_tpu import kernel_tier
from mxnet_tpu.ops.registry import get_op

pytestmark = pytest.mark.skipif(
    len(jax.devices("cpu")) < 8, reason="needs 8 virtual cpu devices")

V, D, L, H, T, B = 64, 32, 2, 4, 8, 4


def _qkv(seed=0, b=2, h=2, t=8, d=4, dtype=np.float32):
    rs = np.random.RandomState(seed)
    return tuple(jnp.asarray(rs.randn(b, h, t, d).astype(dtype))
                 for _ in range(3))


def _seq_plan(data=2, seq=4):
    return SpmdPlan(SpmdPlan.build_mesh_for(
        jax.devices("cpu")[:data * seq], MeshConfig(data=data, seq=seq)))


def _init(mod):
    mod.init_params(mx.initializer.Xavier(rnd_type="gaussian",
                                          magnitude=2))


# ===================================================== symbol structure
def test_symbol_shapes_and_tying():
    sym = tfm.get_symbol(vocab_size=V, d_model=D, n_layer=L, n_head=H,
                         seq_len=T)
    args, outs, auxs = sym.infer_shape(data=(B, T),
                                       softmax_label=(B * T,))
    by_name = dict(zip(sym.list_arguments(), args))
    assert by_name["lm_tok_embed_weight"] == (V, D)
    assert outs == [(B * T, V)]
    assert sym.list_auxiliary_states() == []
    # tied head: exactly ONE embedding-sized weight in the graph
    assert sum(1 for n, s in by_name.items() if s == (V, D)) == 1
    # learned positions add the table
    sym2 = tfm.get_symbol(vocab_size=V, d_model=D, n_layer=1, n_head=H,
                          seq_len=T, pos_embed="learned", max_seq_len=16)
    args2, _, _ = sym2.infer_shape(data=(B, T), softmax_label=(B * T,))
    by2 = dict(zip(sym2.list_arguments(), args2))
    assert by2["lm_pos_embed_weight"] == (16, D)


def test_synthetic_lm_iter_contract():
    it = tfm.SyntheticLMIter(V, B, T, n_batches=3, seed=0)
    assert it.provide_data[0].shape == (B, T)
    assert np.dtype(it.provide_data[0].dtype) == np.int32
    assert it.provide_label[0].shape == (B * T,)
    batches = list(it)
    assert len(batches) == 3
    d = batches[0].data[0].asnumpy()
    l = batches[0].label[0].asnumpy()
    assert d.dtype == np.int32 and d.shape == (B, T)
    # labels are the shifted-by-one stream, flattened row-major
    assert l.shape == (B * T,)
    assert (l.reshape(B, T)[:, :-1] == d[:, 1:]).all()


# ================================================== ring resurrection
@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_parity_forward(causal):
    """Satellite: ring == full attention on a seq-axis mesh (the PR-0
    dead code, now gated for real against the attention contract)."""
    from mxnet_tpu.parallel.mesh import build_mesh
    q, k, v = _qkv(0, 2, 2, 8, 4)
    mesh = build_mesh(MeshConfig(seq=4), devices=jax.devices("cpu")[:4])
    got = ring_attention_sharded(q, k, v, mesh, causal=causal)
    ref = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_parity_grad(causal):
    """Ring gradients == full-attention gradients (the training path
    differentiates through the ppermute ring)."""
    from mxnet_tpu.parallel.collectives import shard_map
    from mxnet_tpu.parallel.ring_attention import ring_attention
    from jax.sharding import PartitionSpec as P
    import functools

    q, k, v = _qkv(1, 2, 2, 8, 4)
    mesh = _seq_plan(1, 4).mesh
    spec = P(None, None, "seq", None)
    ring = shard_map(
        functools.partial(ring_attention, axis_name="seq", causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    w = jnp.asarray(np.random.RandomState(2).randn(*q.shape)
                    .astype(np.float32))

    g_ring = jax.grad(lambda *a: jnp.sum(ring(*a) * w),
                      argnums=(0, 1, 2))(q, k, v)
    g_full = jax.grad(
        lambda *a: jnp.sum(full_attention(*a, causal=causal) * w),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_full):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


# ============================================ three gated lowerings
def test_attention_has_three_gated_lowerings():
    opdef = get_op("attention")
    assert set(opdef.variants) == {"pallas", "ring"}  # + the xla forward
    shapes, dtypes = [(2, 2, 8, 4)] * 3, ["float32"] * 3
    # no plan: ring ineligible, CPU auto resolves to the composition
    assert not opdef.variant_eligible("ring", {}, shapes, dtypes)
    assert kernel_tier.resolve(opdef, {}, shapes, dtypes, True) == "xla"
    plan = _seq_plan(2, 4)
    with spmd_mod.plan_scope(plan):
        assert opdef.variant_eligible("ring", {}, shapes, dtypes)
        # indivisible T: never eligible
        assert not opdef.variant_eligible("ring", {}, [(2, 2, 6, 4)] * 3,
                                          dtypes)
    assert kernel_tier.resolve(opdef, {}, shapes, dtypes, True,
                               spmd_plan=plan) == "ring"
    assert any(d.get("variant") == "ring" and d.get("source") == "plan"
               for d in kernel_tier.decisions())


def test_attention_ring_numerics_gate():
    """The ring lowering passes the SAME numerics gate the flash kernel
    does, f32 and bf16."""
    opdef = get_op("attention")
    plan = _seq_plan(1, 4)
    for dt, tol in (("float32", None), ("bfloat16", None)):
        with spmd_mod.plan_scope(plan):
            ok, err = kernel_tier.numerics_gate(
                opdef, {"causal": True}, [(2, 2, 8, 4)] * 3, [dt] * 3,
                variant="ring", is_train=True, n_aux=0)
        assert ok, f"ring numerics gate failed at {dt}: {err}"


def test_attention_flash_numerics_gate():
    """The fused (flash) lowering stays gated too — interpret mode off
    TPU, same tolerance table."""
    opdef = get_op("attention")
    for dt in ("float32", "bfloat16"):
        ok, err = kernel_tier.numerics_gate(
            opdef, {"causal": True}, [(1, 2, 8, 4)] * 3, [dt] * 3,
            variant="pallas", is_train=False, n_aux=0)
        assert ok, f"flash numerics gate failed at {dt}: {err}"


def test_kernel_tier_xla_mode_overrides_ring(monkeypatch):
    monkeypatch.setenv("MXNET_KERNEL_TIER", "xla")
    opdef = get_op("attention")
    assert kernel_tier.resolve(opdef, {}, [(2, 2, 8, 4)] * 3,
                               ["float32"] * 3, True,
                               spmd_plan=_seq_plan(2, 4)) == "xla"


# ========================================== (data x seq) spmd training
def _fit_lm(spmd, K=1, n_dev=1, mesh=None):
    mx.random.seed(7)
    sym = tfm.get_symbol(vocab_size=V, d_model=D, n_layer=L, n_head=H,
                         seq_len=T)
    it = tfm.SyntheticLMIter(V, B, T, n_batches=4, seed=0)
    mod = mx.mod.Module(sym, context=[mx.cpu(i) for i in range(n_dev)])
    accs = []
    mod.fit(it, num_epoch=2, spmd=spmd, mesh=mesh, steps_per_dispatch=K,
            optimizer="sgd",
            optimizer_params=(("learning_rate", 0.1), ("momentum", 0.9)),
            batch_end_callback=lambda p: accs.append(
                p.eval_metric.get()[1]),
            initializer=mx.initializer.Xavier(rnd_type="gaussian",
                                              magnitude=2))
    args, _ = mod.get_params()
    return {k: v.asnumpy() for k, v in args.items()}, accs, mod


@pytest.mark.parametrize("K", [1, 4])
def test_spmd_seq_parallel_fit_parity(K):
    """Acceptance: fit(spmd=True) on the (data=2 x seq=2) mesh matches
    the single-device unsharded run — params to float ulps, per-batch
    metric trajectory exactly — at K=1 and under the K=4 scan, with the
    ring lowering actually selected."""
    kernel_tier.clear()
    p0, a0, _ = _fit_lm(False)
    p1, a1, mod = _fit_lm(True, K=K, n_dev=4,
                          mesh=MeshConfig(data=2, seq=2))
    assert mod._fused_armed
    if K > 1:
        assert mod._exec_group._scan_K == K
    for k in p0:
        np.testing.assert_allclose(p0[k], p1[k], rtol=1e-5, atol=1e-6,
                                   err_msg=k)
    np.testing.assert_allclose(a0, a1, rtol=1e-6)
    assert any(d.get("variant") == "ring"
               for d in kernel_tier.decisions())
    plan = mod._exec_group._spmd_plan
    from jax.sharding import PartitionSpec as P
    assert plan.data_spec_for((B, T)) == P("data", "seq")
    # bound token batch really is (data x seq)-sharded
    sh = mod._exec_group.executor.arg_dict["data"].asjax().sharding
    assert sh.is_equivalent_to(plan.data_sharding_for((B, T)), 2)


def test_spmd_seq_parallel_lint_clean():
    """SH6xx stays quiet on the (data x seq) binding (zero-FP gate)."""
    from mxnet_tpu import analysis
    _, _, mod = _fit_lm(True, n_dev=4, mesh=MeshConfig(data=2, seq=2))
    report = analysis.run_passes(
        analysis.AnalysisContext(symbol=mod._symbol,
                                 executor=mod._exec_group.executor,
                                 exec_group=mod._exec_group, module=mod),
        passes=["sharding_checker"])
    assert len(report) == 0, [str(d) for d in report]


# ===================================================== KV-cache decode
def _trained_pair(compute_dtype=None, pos_embed="rotary", n_layer=L):
    full_sym = tfm.get_symbol(vocab_size=V, d_model=D, n_layer=n_layer,
                              n_head=H, seq_len=T, include_loss=False,
                              pos_embed=pos_embed, max_seq_len=T)
    full = mx.mod.Module(full_sym, label_names=[],
                         compute_dtype=compute_dtype)
    full.bind([("data", (B, T))], None, for_training=False)
    _init(full)
    args, _ = full.get_params()

    dec_sym = tfm.get_decode_symbol(
        vocab_size=V, d_model=D, n_layer=n_layer, n_head=H, capacity=T,
        pos_embed=pos_embed, max_seq_len=T)
    data_names = ("data", "pos_ids") if pos_embed == "learned" \
        else ("data",)
    shapes = [("data", (B, 1))] + ([("pos_ids", (1,))]
                                   if pos_embed == "learned" else [])
    dec = mx.mod.Module(dec_sym, data_names=data_names, label_names=[],
                        compute_dtype=compute_dtype)
    dec.bind(shapes, None, for_training=False)
    dec.init_params(initializer=None, arg_params=args, aux_params={},
                    allow_missing=True)
    return full, dec, args


@pytest.mark.parametrize("compute_dtype,tol", [
    (None, 2e-6), ("bfloat16", 2e-2)])
def test_incremental_decode_matches_full_forward(compute_dtype, tol):
    """Acceptance: N single-token KV-cache steps == the length-N full
    forward, f32 (tight) and bf16 (kernel-tier tolerance)."""
    full, dec, _ = _trained_pair(compute_dtype)
    tokens = np.random.RandomState(3).randint(0, V, (B, T)).astype(
        np.int32)
    full.forward(mx.io.DataBatch(data=[mx.nd.array(tokens)], label=[]),
                 is_train=False)
    ref = full.get_outputs()[0].asnumpy().astype(np.float32)

    drv = tfm.KVCacheDecoder(dec, capacity=T)
    got = np.concatenate(
        [drv.step(tokens[:, t:t + 1]).asnumpy().astype(np.float32)
         for t in range(T)], axis=1)
    np.testing.assert_allclose(got, ref, rtol=tol, atol=tol)

    # reset rewinds to a bit-identical step 0
    drv.reset()
    again = drv.step(tokens[:, :1]).asnumpy().astype(np.float32)
    np.testing.assert_array_equal(again[:, 0], got[:, 0])


def test_decode_learned_positions():
    full, dec, _ = _trained_pair(pos_embed="learned", n_layer=1)
    tokens = np.random.RandomState(4).randint(0, V, (B, T)).astype(
        np.int32)
    full.forward(mx.io.DataBatch(data=[mx.nd.array(tokens)], label=[]),
                 is_train=False)
    ref = full.get_outputs()[0].asnumpy()
    drv = tfm.KVCacheDecoder(dec, capacity=T, pos_embed="learned")
    got = np.concatenate([drv.step(tokens[:, t:t + 1]).asnumpy()
                          for t in range(T)], axis=1)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=2e-6)


def test_decode_cache_overflow_raises():
    _full, dec, _ = _trained_pair(n_layer=1)
    tokens = np.zeros((B, 1), np.int32)
    drv = tfm.KVCacheDecoder(dec, capacity=T)
    for _ in range(T):
        drv.step(tokens)
    with pytest.raises(mx.base.MXNetError, match="overflow"):
        drv.step(tokens)
    # eager op-level check too (concrete cursor at capacity)
    op = get_op("attention_decode")
    q = jnp.zeros((1, 1, 1, 4))
    cache = jnp.zeros((1, 1, 4, 4))
    with pytest.raises(mx.base.MXNetError, match="overflow"):
        op.forward({"capacity": 4}, [q, q, q],
                   [cache, cache, jnp.full((1,), 4, jnp.int32)],
                   False, None)


def test_decode_cache_cursor_binds_int32():
    """The declared aux dtype survives binding (and is therefore exempt
    from the bf16 entry cast — exact positions past 256)."""
    _full, dec, _ = _trained_pair(compute_dtype="bfloat16", n_layer=1)
    exe = dec._exec_group.executor
    cursors = [nm for nm in exe.aux_dict if nm.endswith("cache_pos")]
    assert cursors
    for nm in cursors:
        assert exe.aux_dict[nm].asjax().dtype == jnp.int32


def test_attention_decode_rejects_training():
    op = get_op("attention_decode")
    q = jnp.zeros((1, 1, 1, 4))
    cache = jnp.zeros((1, 1, 4, 4))
    with pytest.raises(mx.base.MXNetError, match="inference"):
        op.forward({"capacity": 4}, [q, q, q],
                   [cache, cache, jnp.zeros((1,), jnp.int32)],
                   True, None)


# ====================================== export + serve the decoder
def test_decode_export_serve_zero_compiles(tmp_path):
    """Acceptance: the exported KV-cache decoder is a stateful artifact
    (Predictor carries the cache), reproduces the module decode, and
    serves through serve() with compile_count() delta == 0 after
    warmup."""
    from mxnet_tpu import predict as predict_mod
    from mxnet_tpu import program_cache as pc

    full, dec, args = _trained_pair(n_layer=1)
    tokens = np.random.RandomState(5).randint(0, V, (B, T)).astype(
        np.int32)
    full.forward(mx.io.DataBatch(data=[mx.nd.array(tokens)], label=[]),
                 is_train=False)
    ref = full.get_outputs()[0].asnumpy()

    path = str(tmp_path / "lm_decode.mxp")
    predict_mod.export_model(
        path, tfm.get_decode_symbol(vocab_size=V, d_model=D, n_layer=1,
                                    n_head=H, capacity=T),
        args, {}, {"data": (B, 1)}, data_dtypes={"data": np.int32})
    p = predict_mod.Predictor(path)
    assert p.stateful
    got = np.concatenate([p.forward(data=tokens[:, t:t + 1])[0].asnumpy()
                          for t in range(T)], axis=1)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=2e-6)
    p.reset_state()
    np.testing.assert_array_equal(
        p.forward(data=tokens[:, :1])[0].asnumpy()[:, 0], got[:, 0])

    p.reset_state()
    server = mx.serve.serve(p, name="lmdec")
    try:
        mark = pc.compile_count()
        outs = []
        for t in range(T):
            h = server.submit({"data": tokens[:, t:t + 1]},
                              model="lmdec")
            outs.append(np.asarray(h.result(timeout=60)[0].asnumpy()))
        assert pc.compile_count() - mark == 0
        assert server.stats()["compiles_since_warmup"] == 0
    finally:
        server.stop()
    np.testing.assert_allclose(np.concatenate(outs, axis=1), ref,
                               rtol=1e-5, atol=2e-6)


# ================================================= RoPE + cost/memplan
def test_rope_op_semantics():
    x = jnp.asarray(np.random.RandomState(0).randn(2, 2, 4, 8)
                    .astype(np.float32))
    op = get_op("RoPE")
    (y0,), _ = op.forward({"offset": 0, "base": 10000.0}, [x], [], False,
                          None)
    # position 0 rotates by angle 0: first token unchanged
    np.testing.assert_allclose(np.asarray(y0[:, :, 0]),
                               np.asarray(x[:, :, 0]), rtol=1e-6)
    # offset semantics: RoPE(x, offset=k)[t] == RoPE(x', 0)[t+k]
    (y3,), _ = op.forward({"offset": 3, "base": 10000.0},
                          [x[:, :, :1]], [], False, None)
    (yfull,), _ = op.forward({"offset": 0, "base": 10000.0},
                             [jnp.concatenate([x] * 1, 2)], [], False,
                             None)
    big = jnp.concatenate([x, x], axis=2)      # position 3 holds x[:, :, 3]
    (yb,), _ = op.forward({"offset": 0, "base": 10000.0}, [big], [],
                          False, None)
    np.testing.assert_allclose(np.asarray(yb[:, :, 3]),
                               np.asarray(
                                   op.forward({"offset": 3,
                                               "base": 10000.0},
                                              [x[:, :, 3:4]], [], False,
                                              None)[0][0][:, :, 0]),
                               rtol=1e-5, atol=1e-6)
    # norm-preserving (rotation)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(y0), axis=-1),
        np.linalg.norm(np.asarray(x), axis=-1), rtol=1e-5)


def test_costs_seeded_and_planner_kv_bytes():
    """Satellite: every new op carries BOTH cost estimators, and the
    memory planner charges the decoder's KV cache under
    attention_decode in the per-op byte table."""
    from mxnet_tpu.ops import cost
    assert cost.partial_cost_ops() == []
    for name in ("RoPE", "attention_decode", "attention"):
        assert get_op(name).has_cost(), name

    from mxnet_tpu.analysis import memplan
    cap = 16
    sym = tfm.get_decode_symbol(vocab_size=V, d_model=D, n_layer=L,
                                n_head=H, capacity=cap)
    plan = memplan.plan_symbol(sym, {"data": (B, 1)}, policy="none",
                               for_training=False)
    # two f32 cache arrays per layer + the int32 cursor
    expect = L * (2 * B * H * cap * (D // H) * 4 + 4)
    assert plan["kv_cache_bytes"] == expect
    assert plan["per_op_bytes"].get("attention_decode") == expect
    # aux accounting covers the cache (itemized into the peak)
    assert plan["aux_bytes"] >= expect

    # training-side plans run at none AND dots (zoo gate)
    train_sym = tfm.get_symbol(vocab_size=V, d_model=D, n_layer=L,
                               n_head=H, seq_len=T)
    shapes = {"data": (B, T), "softmax_label": (B * T,)}
    peaks = {}
    for policy in ("none", "dots"):
        p = memplan.plan_symbol(train_sym, shapes, policy=policy)
        assert p["peak_bytes_per_device"] > 0
        peaks[policy] = p["peak_bytes_per_device"]
    assert peaks["dots"] <= peaks["none"]
    # ME801 trips at a toy capacity
    found = memplan.plan_findings(
        memplan.plan_symbol(train_sym, shapes, policy="none"),
        capacity_bytes=1024)
    assert any(d.rule == "ME801" for d in found)


def test_precision_flow_clean_f32_bf16():
    """Satellite: the transformer binds clean under the precision-flow
    pass at f32 and bf16 (the f32 loss head stays exempt)."""
    from mxnet_tpu import analysis
    for cd in (None, "bfloat16"):
        report = analysis.run_passes(analysis.AnalysisContext(
            symbol=tfm.get_symbol(vocab_size=V, d_model=D, n_layer=1,
                                  n_head=H, seq_len=T),
            known_shapes={"data": (B, T)}, compute_dtype=cd),
            passes=["precision_flow"])
        assert len(report) == 0, [str(d) for d in report]


def test_mxlint_zoo_includes_transformer():
    import sys, os
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import mxlint
    names = [t[0] for t in mxlint._check_corpus()]
    assert "models/transformer" in names
    assert "models/transformer_decode" in names
