"""Module tests (mirrors reference tests/python/unittest/test_module.py)."""
import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal


def _softmax_net(num_hidden=4, num_classes=3):
    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data=data, num_hidden=num_hidden, name="fc1")
    act = mx.sym.Activation(fc, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=num_classes, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def _toy_iter(n=120, dim=6, classes=3, batch=20, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, dim).astype(np.float32)
    w = rng.randn(dim, classes).astype(np.float32)
    y = X.dot(w).argmax(axis=1).astype(np.float32)
    return mx.io.NDArrayIter(X, y, batch_size=batch, shuffle=True)


def test_module_input_names():
    data = mx.sym.var("data")
    out = mx.sym.FullyConnected(data, num_hidden=2, name="fc")
    with pytest.raises(ValueError):
        mx.mod.Module(out, data_names=["wrong_name"], label_names=[])


def test_module_fit_and_score():
    it = _toy_iter()
    mod = mx.mod.Module(_softmax_net(), context=mx.cpu())
    mod.fit(it, num_epoch=15, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5})
    acc = mod.score(it, "acc")[0][1]
    assert acc > 0.9, f"accuracy {acc}"


def test_module_predict_shapes():
    it = _toy_iter()
    mod = mx.mod.Module(_softmax_net(), context=mx.cpu())
    mod.bind(it.provide_data, it.provide_label, for_training=False)
    mod.init_params()
    out = mod.predict(it)
    assert out.shape == (120, 3)
    np.testing.assert_allclose(out.asnumpy().sum(axis=1),
                               np.ones(120), rtol=1e-4)


def test_module_get_set_params():
    it = _toy_iter()
    mod = mx.mod.Module(_softmax_net(), context=mx.cpu())
    mod.bind(it.provide_data, it.provide_label)
    mod.init_params()
    args, auxs = mod.get_params()
    assert "fc1_weight" in args
    mod2 = mx.mod.Module(_softmax_net(), context=mx.cpu())
    mod2.bind(it.provide_data, it.provide_label)
    mod2.set_params(args, auxs)
    a1, _ = mod.get_params()
    a2, _ = mod2.get_params()
    for k in a1:
        assert_almost_equal(a1[k], a2[k])


def test_module_checkpoint_roundtrip():
    it = _toy_iter()
    mod = mx.mod.Module(_softmax_net(), context=mx.cpu())
    mod.fit(it, num_epoch=2, optimizer_params={"learning_rate": 0.1})
    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, "model")
        mod.save_checkpoint(prefix, 2, save_optimizer_states=True)
        assert os.path.exists(f"{prefix}-symbol.json")
        assert os.path.exists(f"{prefix}-0002.params")
        assert os.path.exists(f"{prefix}-0002.states")
        mod2 = mx.mod.Module.load(prefix, 2)
        mod2.bind(it.provide_data, it.provide_label, for_training=False)
        it.reset()
        p1 = mod.predict(it, num_batch=1).asnumpy()
        it.reset()
        p2 = mod2.predict(it, num_batch=1).asnumpy()
        assert_almost_equal(p1, p2, rtol=1e-5)


def test_module_fixed_params():
    it = _toy_iter()
    mod = mx.mod.Module(_softmax_net(), context=mx.cpu(),
                        fixed_param_names=["fc1_weight"])
    mod.bind(it.provide_data, it.provide_label)
    mod.init_params()
    mod.init_optimizer(optimizer_params={"learning_rate": 1.0})
    before = mod._exec_group.executor.arg_dict["fc1_weight"].asnumpy().copy()
    batch = next(iter(it))
    mod.forward_backward(batch)
    mod.update()
    after = mod._exec_group.executor.arg_dict["fc1_weight"].asnumpy()
    assert_almost_equal(before, after)  # frozen
    # non-fixed params did move
    fc2b = mod._exec_group.executor.arg_dict["fc2_weight"].asnumpy()
    assert not np.allclose(
        fc2b, mod._arg_params["fc2_weight"].asnumpy())


def test_module_input_grads():
    data = mx.sym.var("data")
    loss = mx.sym.LinearRegressionOutput(
        data=mx.sym.FullyConnected(data, num_hidden=1, name="fc"),
        name="lin")
    mod = mx.mod.Module(loss, label_names=["lin_label"], context=mx.cpu())
    mod.bind([("data", (4, 3))], [("lin_label", (4, 1))],
             inputs_need_grad=True)
    mod.init_params()
    batch = mx.io.DataBatch(data=[mx.nd.ones((4, 3))],
                            label=[mx.nd.zeros((4, 1))])
    mod.forward_backward(batch)
    grads = mod.get_input_grads()
    assert grads[0].shape == (4, 3)
    assert np.abs(grads[0].asnumpy()).sum() > 0


def test_bucketing_module():
    def sym_gen(seq_len):
        # params must be seq-length independent (shared across buckets)
        data = mx.sym.var("data")
        emb = mx.sym.Embedding(data, input_dim=20, output_dim=4, name="emb")
        pooled = mx.sym.sum(emb, axis=1)
        fc = mx.sym.FullyConnected(pooled, num_hidden=3, name="fc")
        out = mx.sym.SoftmaxOutput(fc, name="softmax")
        return out, ["data"], ["softmax_label"]

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=10,
                                 context=mx.cpu())
    mod.bind([("data", (8, 10))], [("softmax_label", (8,))])
    mod.init_params()
    mod.init_optimizer(optimizer_params={"learning_rate": 0.1})
    rng = np.random.RandomState(0)
    for key in [10, 6, 10, 6]:
        batch = mx.io.DataBatch(
            data=[mx.nd.array(rng.randint(0, 20, (8, key))
                              .astype(np.float32))],
            label=[mx.nd.array(rng.randint(0, 3, 8).astype(np.float32))],
            bucket_key=key,
            provide_data=[mx.io.DataDesc("data", (8, key))],
            provide_label=[mx.io.DataDesc("softmax_label", (8,))])
        mod.forward_backward(batch)
        mod.update()
    assert set(mod._buckets) == {10, 6}
    # params shared across buckets (identity of the cells)
    e10 = mod._buckets[10]._exec_group.executor
    e6 = mod._buckets[6]._exec_group.executor
    assert e10.arg_dict["fc_bias"] is e6.arg_dict["fc_bias"]


def test_sequential_module():
    net1 = mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=4,
                                 name="fc1")
    net2 = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=3, name="fc2"),
        name="softmax")
    mod = mx.mod.SequentialModule()
    mod.add(mx.mod.Module(net1, label_names=[], context=mx.cpu()))
    mod.add(mx.mod.Module(net2, context=mx.cpu()), take_labels=True,
            auto_wiring=True)
    it = _toy_iter(dim=6)
    mod.bind(it.provide_data, it.provide_label)
    mod.init_params()
    mod.init_optimizer(optimizer_params={"learning_rate": 0.5})
    metric = mx.metric.create("acc")
    for _ in range(10):
        it.reset()
        for batch in it:
            mod.forward_backward(batch)
            mod.update()
            mod.update_metric(metric, batch.label)
    assert metric.get()[1] > 0.5


def test_module_multi_device_matches_single():
    """DP over 4 virtual devices must match single-device numerics
    bit-for-bit (up to f32 reduction order): same init -> same params
    after an epoch."""
    def make_iter():
        rng = np.random.RandomState(3)
        X = rng.randn(120, 6).astype(np.float32)
        w = rng.randn(6, 3).astype(np.float32)
        y = X.dot(w).argmax(axis=1).astype(np.float32)
        return mx.io.NDArrayIter(X, y, batch_size=24, shuffle=False)

    args = None
    params_out = []
    for ctxs in [[mx.cpu(0)], [mx.cpu(i) for i in range(4)]]:
        it = make_iter()
        mod = mx.mod.Module(_softmax_net(), context=ctxs)
        mod.bind(it.provide_data, it.provide_label)
        if args is None:
            mx.random.seed(7)
            mod.init_params(mx.initializer.Xavier())
            a, _ = mod.get_params()
            args = {k: v.asnumpy() for k, v in a.items()}
        else:
            mod.init_params(
                arg_params={k: mx.nd.array(v) for k, v in args.items()},
                aux_params={})
        mod.init_optimizer(optimizer_params={"learning_rate": 0.5})
        for batch in it:
            mod.forward_backward(batch)
            mod.update()
        p, _ = mod.get_params()
        params_out.append(p["fc2_weight"].asnumpy())
    assert np.abs(params_out[0] - params_out[1]).max() < 1e-4


def _mlp_sym():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    act = mx.sym.Activation(fc1, act_type="tanh")
    fc2 = mx.sym.FullyConnected(act, num_hidden=3, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, mx.sym.Variable("softmax_label"),
                                name="softmax")


def _run_steps(fused, optimizer, opt_params, steps=5):
    rs = np.random.RandomState(42)
    init_args = {
        "fc1_weight": rs.randn(8, 6).astype(np.float32) * 0.1,
        "fc1_bias": np.zeros(8, np.float32),
        "fc2_weight": rs.randn(3, 8).astype(np.float32) * 0.1,
        "fc2_bias": np.zeros(3, np.float32),
    }
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.bind([("data", (4, 6))], [("softmax_label", (4,))])
    mod.init_params(arg_params={k: mx.nd.array(v)
                                for k, v in init_args.items()})
    mod.init_optimizer(kvstore=None, optimizer=optimizer,
                       optimizer_params=opt_params)
    if fused:
        assert mod._fused_armed, "fused path should arm for " + optimizer
    else:
        mod._fused_armed = False
    for step in range(steps):
        srs = np.random.RandomState(100 + step)
        batch = mx.io.DataBatch(
            data=[mx.nd.array(srs.rand(4, 6).astype(np.float32))],
            label=[mx.nd.array(srs.randint(0, 3, (4,)).astype(np.float32))])
        mod.forward_backward(batch)
        mod.update()
    args, _ = mod.get_params()
    return {k: v.asnumpy() for k, v in args.items()}


@pytest.mark.parametrize("optimizer,opt_params", [
    ("sgd", (("learning_rate", 0.1), ("momentum", 0.9), ("wd", 1e-4))),
    ("adam", (("learning_rate", 0.01), ("wd", 1e-4))),
])
def test_fused_step_matches_staged(optimizer, opt_params):
    """VERDICT r2 #2: the fused fwd+bwd+update program must reproduce the
    staged forward/backward/update numerics over several steps."""
    fused = _run_steps(True, optimizer, opt_params)
    staged = _run_steps(False, optimizer, opt_params)
    for k in fused:
        np.testing.assert_allclose(fused[k], staged[k], rtol=2e-5,
                                   atol=2e-6, err_msg=k)


def test_fused_step_optimizer_state_roundtrip():
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.bind([("data", (4, 6))], [("softmax_label", (4,))])
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(kvstore=None, optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.1),
                                         ("momentum", 0.9)))
    assert mod._fused_armed
    rs = np.random.RandomState(3)
    batch = mx.io.DataBatch(
        data=[mx.nd.array(rs.rand(4, 6).astype(np.float32))],
        label=[mx.nd.array(rs.randint(0, 3, (4,)).astype(np.float32))])
    mod.forward_backward(batch)
    mod.update()
    with tempfile.TemporaryDirectory() as d:
        fname = os.path.join(d, "opt.states")
        mod.save_optimizer_states(fname)
        before = {k: np.asarray(v) for k, v in
                  mod._exec_group._fused_states.items()}
        mod.forward_backward(batch)
        mod.update()
        mod.load_optimizer_states(fname)
        after = {k: np.asarray(v) for k, v in
                 mod._exec_group._fused_states.items()}
    for k in before:
        np.testing.assert_allclose(before[k], after[k])


def test_fused_keep_grads_env(monkeypatch):
    """MXNET_FUSED_KEEP_GRADS=1 makes the fused program emit per-param
    gradients into grad_dict (off by default: they cost ~5%/step)."""
    def grads_after_step(keep):
        monkeypatch.setenv("MXNET_FUSED_KEEP_GRADS", "1" if keep else "0")
        rs = np.random.RandomState(11)
        mx.random.seed(5)                 # identical params every variant
        mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
        mod.bind([("data", (4, 6))], [("softmax_label", (4,))])
        mod.init_params(mx.initializer.Xavier())
        mod.init_optimizer(kvstore=None, optimizer="sgd",
                           optimizer_params=(("learning_rate", 0.0),))
        assert mod._fused_armed
        gd = mod._exec_group.executor.grad_dict
        before = {k: v.asnumpy().copy() for k, v in gd.items()
                  if v is not None}
        batch = mx.io.DataBatch(
            data=[mx.nd.array(rs.rand(4, 6).astype(np.float32))],
            label=[mx.nd.array(rs.randint(0, 3, (4,)).astype(np.float32))])
        mod.forward_backward(batch)
        after = {k: v.asnumpy() for k, v in gd.items() if v is not None}
        changed = any(np.abs(after[k] - before[k]).max() > 0
                      for k in after)
        return changed, after

    changed_off, grads_off = grads_after_step(False)
    assert not changed_off, "default fused step must not write grad_dict"
    # ADVICE r5: with KEEP_GRADS unset the fused path never emits grads —
    # the buffers are NaN-poisoned at arm time so a stale read fails
    # loudly instead of returning plausible pre-step values
    for k, v in grads_off.items():
        assert np.isnan(v).all(), f"{k} not poisoned"
    changed_on, grads_fused = grads_after_step(True)
    assert changed_on, "KEEP_GRADS=1 must populate grad_dict"
    # and the emitted gradients match the staged path's
    rs = np.random.RandomState(11)
    mx.random.seed(5)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.bind([("data", (4, 6))], [("softmax_label", (4,))])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(kvstore=None, optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.0),))
    mod._fused_armed = False                      # staged path
    batch = mx.io.DataBatch(
        data=[mx.nd.array(rs.rand(4, 6).astype(np.float32))],
        label=[mx.nd.array(rs.randint(0, 3, (4,)).astype(np.float32))])
    mod.forward_backward(batch)
    for k, v in mod._exec_group.executor.grad_dict.items():
        if v is not None:
            np.testing.assert_allclose(grads_fused[k], v.asnumpy(),
                                       rtol=2e-5, atol=2e-6, err_msg=k)


def test_fused_metric_scalars_match_staged_accuracy():
    """The fused program's in-step top-1 counts must reproduce exactly
    what Accuracy computes from the outputs (zero-dispatch metric)."""
    rs = np.random.RandomState(9)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.bind([("data", (8, 6))], [("softmax_label", (8,))])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(kvstore=None, optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.0),))
    assert mod._fused_armed
    fused_acc = mx.metric.create("acc")
    ref_acc = mx.metric.create("acc")
    for _ in range(3):
        batch = mx.io.DataBatch(
            data=[mx.nd.array(rs.rand(8, 6).astype(np.float32))],
            label=[mx.nd.array(rs.randint(0, 3, (8,)).astype(np.float32))])
        mod.forward_backward(batch)
        assert mod._exec_group._fused_metric_scalars is not None
        mod.update_metric(fused_acc, batch.label)
        assert mod._exec_group._fused_metric_scalars is None  # consumed
        ref_acc.update(batch.label, mod.get_outputs())
    assert fused_acc.get() == ref_acc.get()
    # an eval pass right after a fused step must not consume train counts
    batch = mx.io.DataBatch(
        data=[mx.nd.array(rs.rand(8, 6).astype(np.float32))],
        label=[mx.nd.array(rs.randint(0, 3, (8,)).astype(np.float32))])
    mod.forward_backward(batch)                 # scalars armed...
    mod.forward(batch, is_train=False)          # ...invalidated by eval
    assert mod._exec_group._fused_metric_scalars is None


def test_fused_rng_reseed_mid_training():
    """mx.random.seed() between steps must re-draw the fused step's
    device-chained rng key (reference seed semantics: seeding is
    effective at any point, not just before arming)."""
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.bind([("data", (4, 6))], [("softmax_label", (4,))])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(kvstore=None, optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.1),))
    assert mod._fused_armed
    eg = mod._exec_group
    rs = np.random.RandomState(3)
    batch = mx.io.DataBatch(
        data=[mx.nd.array(rs.rand(4, 6).astype(np.float32))],
        label=[mx.nd.array(rs.randint(0, 3, (4,)).astype(np.float32))])
    mod.forward_backward(batch)
    key_before = np.asarray(eg._fused_key).copy()
    mx.random.seed(42)
    mod.forward_backward(batch)        # must re-draw from new chain
    mx.random.seed(42)
    fresh = np.asarray(mx.random.next_key())
    # the chain was re-drawn at the step boundary: the key in use after
    # reseed+step is the successor of the reseeded chain's first subkey,
    # not a continuation of the pre-seed chain
    assert not np.array_equal(np.asarray(eg._fused_key), key_before)
    import jax
    expect = np.asarray(jax.random.split(fresh)[0])
    np.testing.assert_array_equal(np.asarray(eg._fused_key), expect)


def test_set_params_after_arming_does_not_donate_caller_buffer():
    """set_params after the fused step is armed must copy: astype/
    device_put are identity when dtype+placement match, and the next
    step's donation would otherwise delete a buffer the caller holds."""
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.bind([("data", (4, 6))], [("softmax_label", (4,))])
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(kvstore=None, optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.1),))
    assert mod._fused_armed
    rs = np.random.RandomState(7)
    batch = mx.io.DataBatch(
        data=[mx.nd.array(rs.rand(4, 6).astype(np.float32))],
        label=[mx.nd.array(rs.randint(0, 3, (4,)).astype(np.float32))])
    mod.forward_backward(batch)
    mod.update()
    # caller-held arrays, already in matching dtype/placement
    args, aux = mod.get_params()
    held = {k: v.asjax() for k, v in args.items()}
    mod.set_params(args, aux)
    mod.forward_backward(batch)          # donated step runs again
    mod.update()
    for k, v in held.items():            # caller buffers must survive
        np.asarray(v)


def test_fused_step_matches_staged_with_scheduler():
    """lr scheduler must see the same update count in both paths."""
    def params():
        return (("learning_rate", 0.2), ("momentum", 0.9),
                ("lr_scheduler",
                 mx.lr_scheduler.FactorScheduler(step=2, factor=0.5)))
    fused = _run_steps(True, "sgd", params())
    staged = _run_steps(False, "sgd", params())
    for k in fused:
        np.testing.assert_allclose(fused[k], staged[k], rtol=2e-5,
                                   atol=2e-6, err_msg=k)
