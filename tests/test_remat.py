"""Remat policy: residual shrink, numerics parity, cache keying,
donation, batch-bucket headroom.

All on the CPU mesh: ``remat.residual_bytes`` is a pure trace
(jax.eval_shape), so the memory gate is exact and backend-independent.
"""
import numpy as np
import jax.numpy as jnp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import program_cache, remat
from mxnet_tpu.models import resnet


@pytest.fixture(autouse=True)
def _clean_policy(monkeypatch):
    monkeypatch.delenv("MXNET_REMAT_POLICY", raising=False)
    remat.set_active(None)
    yield
    remat.set_active(None)


def test_policy_resolution(monkeypatch):
    assert remat.active() == "none"
    monkeypatch.setenv("MXNET_REMAT_POLICY", "dots")
    assert remat.active() == "dots"
    monkeypatch.setenv("MXNET_REMAT_POLICY", "garbage")
    assert remat.active() == "none"
    assert remat.set_active("all") == "all"
    monkeypatch.setenv("MXNET_REMAT_POLICY", "dots")
    assert remat.active() == "all"        # explicit override wins
    remat.set_active(None)
    assert remat.active() == "dots"
    with pytest.raises(ValueError):
        remat.resolve("sometimes")


RESNET_BATCH = 16


def _resnet_symbol(num_layers=20):
    return resnet.get_symbol(num_classes=10, num_layers=num_layers,
                             image_shape="3,32,32")


def _arm_resnet(policy, batch=RESNET_BATCH, num_layers=20):
    """Bind + arm the fused step WITHOUT running it: jit is lazy, and
    fused_memory_report is a pure trace — the memory-gate tests at the
    resnet20 bench point never pay a compile."""
    mx.random.seed(0)
    mod = mx.mod.Module(_resnet_symbol(num_layers), context=mx.cpu())
    mod.bind([("data", (batch, 3, 32, 32))],
             [("softmax_label", (batch,))])
    mod.init_params(mx.initializer.Xavier())
    remat.set_active(policy)
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.05,
                                         "momentum": 0.9})
    remat.set_active(None)
    assert mod._fused_armed
    assert mod._exec_group._remat_policy == (policy or "none")
    return mod


def _fit_resnet(policy, batches=4, batch=8, K=1, num_layers=8):
    """Short real training run (compiles) — the numerics-parity tests;
    resnet8/b8 keeps per-policy compile time inside the tier-1 budget
    while exercising the same BN/conv graph structure."""
    mx.random.seed(0)
    rng = np.random.RandomState(0)
    imgs = rng.rand(batches * batch, 3, 32, 32).astype(np.float32)
    labels = (rng.rand(batches * batch) * 10).astype(np.float32)
    it = mx.io.NDArrayIter(imgs, labels, batch_size=batch)
    mod = mx.mod.Module(_resnet_symbol(num_layers), context=mx.cpu())
    mod.fit(it, num_epoch=1, initializer=mx.initializer.Xavier(),
            optimizer_params={"learning_rate": 0.05, "momentum": 0.9},
            remat=policy, steps_per_dispatch=K)
    assert mod._fused_armed
    return mod


def test_residual_bytes_drop_on_resnet20():
    """The memory-accountant gate: peak live bytes between fwd and bwd
    measurably drop under the non-none policies (acceptance: remat=all
    reduces peak live bytes at the resnet20 bench point)."""
    reports = {}
    for policy in ("none", "dots", "all"):
        mod = _arm_resnet(policy)
        reports[policy] = mod._exec_group.fused_memory_report()
        program_cache.clear()
    r_none = reports["none"]["residual_bytes"]
    r_dots = reports["dots"]["residual_bytes"]
    r_all = reports["all"]["residual_bytes"]
    assert r_all < r_dots < r_none
    # `all` saves only the inputs: the drop is drastic, not marginal
    assert r_all < 0.1 * r_none
    assert reports["none"]["policy"] == "none"
    assert reports["all"]["policy"] == "all"


def test_headroom_admits_next_larger_bucket():
    """The freed residual bytes convert into batch: with a budget
    calibrated so `none` just fits the bench batch, the accountant
    admits the NEXT-LARGER bucket under a remat policy."""
    from mxnet_tpu.telemetry.memory import batch_headroom
    per_sample, fixed = {}, None
    for policy in ("none", "all"):
        mod = _arm_resnet(policy)
        rep = mod._exec_group.fused_memory_report()
        per_sample[policy] = (rep["residual_bytes"]
                              + rep["batch_bytes"]) / RESNET_BATCH
        fixed = rep["param_bytes"] + rep["state_bytes"]
        program_cache.clear()
    buckets = (RESNET_BATCH, 2 * RESNET_BATCH, 4 * RESNET_BATCH)
    budget = fixed + per_sample["none"] * RESNET_BATCH
    assert batch_headroom(budget, fixed, per_sample["none"],
                          buckets) == RESNET_BATCH
    assert batch_headroom(budget, fixed, per_sample["all"],
                          buckets) > RESNET_BATCH
    assert batch_headroom(0, fixed, per_sample["all"], buckets) is None


def test_fit_bit_identical_across_policies():
    """Remat recomputes the same ops — trained params are bit-identical
    under every policy (and donation of rng/aux changes nothing)."""
    digests = {}
    for policy in ("none", "dots", "all"):
        mod = _fit_resnet(policy)
        ap, xp = mod.get_params()
        digests[policy] = {k: v.asnumpy() for k, v in ap.items()}
        digests[policy].update(
            {f"aux:{k}": v.asnumpy() for k, v in xp.items()})
        program_cache.clear()
    for policy in ("dots", "all"):
        for k, v in digests["none"].items():
            np.testing.assert_array_equal(
                v, digests[policy][k],
                err_msg=f"{policy} diverged at {k}")


def test_scan_window_bit_identical_under_remat():
    """K-step scan inherits the policy through step_core: K=4 windows
    under remat=all match K=4 under none bit for bit (same dispatch
    shape — scan-vs-single is a separate, policy-independent program
    and XLA's float scheduling differs between them)."""
    ref = _fit_resnet("none", batches=4, K=4)
    assert ref._exec_group._scan_K == 4
    ap_ref, _ = ref.get_params()
    program_cache.clear()
    got = _fit_resnet("all", batches=4, K=4)
    assert got._exec_group._scan_K == 4
    ap_got, _ = got.get_params()
    for k in ap_ref:
        np.testing.assert_array_equal(ap_ref[k].asnumpy(),
                                      ap_got[k].asnumpy())


def test_policy_keys_program_cache():
    """A fused program traced under one policy is never reused under
    another: the cache keys differ in the remat token."""
    mod_a = _arm_resnet("none")
    key_a = mod_a._exec_group._fused_cache_key
    program_cache.clear()
    mod_b = _arm_resnet("all")
    key_b = mod_b._exec_group._fused_cache_key
    assert key_a is not None and key_b is not None
    assert key_a != key_b
    assert ("remat", "none") in key_a
    assert ("remat", "all") in key_b


def test_donation_set_per_policy():
    """none keeps the pre-knob donation (params, states); a policy adds
    the rng chain and — resnet's BN refreshes every aux — the aux
    buffers."""
    mod = _arm_resnet("none")
    assert mod._exec_group._fused_donate == (0, 4)
    program_cache.clear()
    mod = _arm_resnet("dots")
    assert mod._exec_group._fused_donate == (0, 2, 3, 4)


def test_env_policy_drives_fit(monkeypatch):
    """MXNET_REMAT_POLICY alone (no kwarg) arms the policy."""
    monkeypatch.setenv("MXNET_REMAT_POLICY", "all")
    mod = _fit_resnet(None, batches=2)
    assert mod._exec_group._remat_policy == "all"
    rep = mod._exec_group.fused_memory_report()
    assert rep["policy"] == "all"


def test_eval_after_remat_step_reads_fresh_aux():
    """Aux donation must not break the eval path: score() right after
    remat-policy training reads valid (fresh) aux buffers."""
    mod = _fit_resnet("all", batches=2)
    rng = np.random.RandomState(1)
    imgs = rng.rand(8, 3, 32, 32).astype(np.float32)
    labels = (rng.rand(8) * 10).astype(np.float32)
    it = mx.io.NDArrayIter(imgs, labels, batch_size=8)
    res = mod.score(it, "acc")
    assert 0.0 <= dict(res)["accuracy"] <= 1.0
