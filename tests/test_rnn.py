"""RNN tests (mirrors reference tests/python/unittest/test_rnn.py —
cell unroll shapes + fused/unfused equivalence)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal


def test_rnn_cell_unroll_shapes():
    cell = mx.rnn.RNNCell(10, prefix="rnn_")
    outputs, states = cell.unroll(3, input_prefix="rnn_")
    outputs = mx.sym.Group(outputs)
    assert sorted(cell.params._params.keys()) == [
        "rnn_h2h_bias", "rnn_h2h_weight", "rnn_i2h_bias", "rnn_i2h_weight"]
    args, outs, _ = outputs.infer_shape(rnn_t0_data=(10, 50),
                                        rnn_t1_data=(10, 50),
                                        rnn_t2_data=(10, 50))
    assert outs == [(10, 10)] * 3


def test_lstm_cell_unroll():
    cell = mx.rnn.LSTMCell(10, prefix="lstm_")
    outputs, states = cell.unroll(3, input_prefix="lstm_")
    assert len(states) == 2
    outputs = mx.sym.Group(outputs)
    args, outs, _ = outputs.infer_shape(lstm_t0_data=(8, 20),
                                        lstm_t1_data=(8, 20),
                                        lstm_t2_data=(8, 20))
    assert outs == [(8, 10)] * 3
    named = dict(zip(outputs.list_arguments(), args))
    assert named["lstm_i2h_weight"] == (40, 20)
    assert named["lstm_h2h_weight"] == (40, 10)


def test_gru_cell_unroll():
    cell = mx.rnn.GRUCell(10, prefix="gru_")
    outputs, _ = cell.unroll(3, input_prefix="gru_")
    outputs = mx.sym.Group(outputs)
    _, outs, _ = outputs.infer_shape(gru_t0_data=(4, 7),
                                     gru_t1_data=(4, 7),
                                     gru_t2_data=(4, 7))
    assert outs == [(4, 10)] * 3


def test_stacked_and_bidirectional():
    stack = mx.rnn.SequentialRNNCell()
    stack.add(mx.rnn.LSTMCell(8, prefix="l0_"))
    stack.add(mx.rnn.LSTMCell(8, prefix="l1_"))
    outputs, states = stack.unroll(2, input_prefix="s_")
    assert len(states) == 4
    bi = mx.rnn.BidirectionalCell(mx.rnn.LSTMCell(5, prefix="l_"),
                                  mx.rnn.LSTMCell(5, prefix="r_"))
    outputs, states = bi.unroll(3, input_prefix="b_")
    out = mx.sym.Group(outputs)
    _, outs, _ = out.infer_shape(b_t0_data=(2, 4), b_t1_data=(2, 4),
                                 b_t2_data=(2, 4))
    assert outs == [(2, 10)] * 3  # concat of both directions


def test_fused_rnn_op_shapes():
    data = mx.sym.var("data")
    rnn = mx.sym.RNN(data=data, state_size=6, num_layers=2, mode="lstm",
                     state_outputs=True, name="rnn")
    args, outs, _ = rnn.infer_shape(data=(5, 3, 4))
    named = dict(zip(rnn.list_arguments(), args))
    assert outs[0] == (5, 3, 12) or outs[0] == (5, 3, 6)
    # lstm: 4 gates; layer0: 4*6*(4+6+2)... exact total from pack math
    assert named["rnn_state"] == (2, 3, 6)
    ex = rnn.simple_bind(ctx=mx.cpu(), data=(5, 3, 4))
    ex.arg_dict["data"][:] = np.random.rand(5, 3, 4).astype(np.float32)
    outs = ex.forward()
    assert outs[0].shape == (5, 3, 6)
    assert outs[1].shape == (2, 3, 6)
    assert outs[2].shape == (2, 3, 6)


def test_fused_vs_unfused_lstm():
    """Fused RNN op == unrolled LSTMCell stack on the same packed weights
    (the reference's weight pack/unpack equivalence contract)."""
    T, N, C, H = 4, 2, 3, 5
    fused = mx.rnn.FusedRNNCell(H, num_layers=1, mode="lstm",
                                prefix="lstm_")
    # fused op graph
    data = mx.sym.var("data")
    rnn = mx.sym.RNN(data=data, parameters=mx.sym.var("lstm_parameters"),
                     state=mx.sym.var("lstm_state"),
                     state_cell=mx.sym.var("lstm_state_cell"),
                     state_size=H, num_layers=1, mode="lstm", name="rnn")
    ex = rnn.simple_bind(ctx=mx.cpu(), data=(T, N, C))
    rng = np.random.RandomState(0)
    x_np = rng.randn(T, N, C).astype(np.float32)
    params_np = rng.randn(*ex.arg_dict["lstm_parameters"].shape) \
        .astype(np.float32) * 0.3
    ex.arg_dict["data"][:] = x_np
    ex.arg_dict["lstm_parameters"][:] = params_np
    fused_out = ex.forward()[0].asnumpy()

    # unfused: unpack the same blob into cell weights, unroll
    args = fused.unpack_weights(
        {"lstm_parameters": mx.nd.array(params_np)})
    cell = mx.rnn.LSTMCell(H, prefix="lstm_l0_")
    outputs, _ = cell.unroll(
        T, inputs=[mx.sym.var(f"t{i}") for i in range(T)])
    group = mx.sym.Group(outputs)
    feed = {f"t{i}": mx.nd.array(x_np[i]) for i in range(T)}
    feed.update({k: v for k, v in args.items()})
    feed.update({f"lstm_l0_begin_state_{i}": mx.nd.zeros((N, H))
                 for i in range(2)})
    ex2 = group.bind(mx.cpu(), args=feed)
    unfused_outs = np.stack([o.asnumpy() for o in ex2.forward()])
    assert_almost_equal(fused_out, unfused_outs, rtol=1e-4, atol=1e-5)


def test_fused_vs_unfused_gru():
    T, N, C, H = 3, 2, 4, 5
    fused = mx.rnn.FusedRNNCell(H, num_layers=1, mode="gru", prefix="gru_")
    data = mx.sym.var("data")
    rnn = mx.sym.RNN(data=data, parameters=mx.sym.var("gru_parameters"),
                     state=mx.sym.var("gru_state"),
                     state_size=H, num_layers=1, mode="gru", name="rnn")
    ex = rnn.simple_bind(ctx=mx.cpu(), data=(T, N, C))
    rng = np.random.RandomState(1)
    x_np = rng.randn(T, N, C).astype(np.float32)
    params_np = rng.randn(*ex.arg_dict["gru_parameters"].shape) \
        .astype(np.float32) * 0.3
    ex.arg_dict["data"][:] = x_np
    ex.arg_dict["gru_parameters"][:] = params_np
    fused_out = ex.forward()[0].asnumpy()

    args = fused.unpack_weights({"gru_parameters": mx.nd.array(params_np)})
    cell = mx.rnn.GRUCell(H, prefix="gru_l0_")
    outputs, _ = cell.unroll(
        T, inputs=[mx.sym.var(f"t{i}") for i in range(T)])
    group = mx.sym.Group(outputs)
    feed = {f"t{i}": mx.nd.array(x_np[i]) for i in range(T)}
    feed.update(args)
    feed.update({"gru_l0_begin_state_0": mx.nd.zeros((N, H))})
    ex2 = group.bind(mx.cpu(), args=feed)
    unfused_outs = np.stack([o.asnumpy() for o in ex2.forward()])
    assert_almost_equal(fused_out, unfused_outs, rtol=1e-4, atol=1e-5)


def test_pack_unpack_roundtrip():
    fused = mx.rnn.FusedRNNCell(6, num_layers=2, mode="lstm",
                                bidirectional=True, prefix="f_")
    total = fused._num_params(8)
    blob = mx.nd.array(np.random.rand(total).astype(np.float32))
    args = fused.unpack_weights({"f_parameters": blob})
    packed = fused.pack_weights(args)
    assert_almost_equal(packed["f_parameters"], blob)


def test_dropout_residual_zoneout_cells():
    base = mx.rnn.RNNCell(4, prefix="b_")
    res = mx.rnn.ResidualCell(mx.rnn.RNNCell(4, prefix="r_"))
    outputs, _ = res.unroll(2, inputs=[mx.sym.var("x0"), mx.sym.var("x1")])
    out = mx.sym.Group(outputs)
    _, outs, _ = out.infer_shape(x0=(2, 4), x1=(2, 4))
    assert outs == [(2, 4)] * 2
    dc = mx.rnn.DropoutCell(0.5)
    assert dc.state_info == []


def test_bucket_sentence_iter():
    sentences = [[1, 2, 3], [2, 3], [1, 2, 3, 4, 5], [3, 4], [1, 2]] * 4
    it = mx.rnn.BucketSentenceIter(sentences, batch_size=4,
                                   buckets=[3, 6], invalid_label=0)
    batch = next(iter(it))
    assert batch.data[0].shape[0] == 4
    assert batch.bucket_key in (3, 6)
