"""Gradient mirroring (remat) + per-op profiler naming.

Mirrors reference capabilities: MXNET_BACKWARD_DO_MIRROR trades recompute
for activation memory (reference: graph_executor.cc:210-223, env_var.md:
62-67); PROFILER_MESSAGE carries per-op names into traces (reference:
threaded_engine.h:296-307).
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import mxnet_tpu as mx


def _deep_lstm_symbol(T=24, H=128):
    cell = mx.rnn.LSTMCell(num_hidden=H, prefix="l0_")
    data = mx.sym.var("data")
    outputs, _ = cell.unroll(T, inputs=data, layout="NTC",
                             merge_outputs=True)
    return mx.sym.LinearRegressionOutput(
        mx.sym.Flatten(outputs), mx.sym.var("label"), name="lro")


def _bind(sym, mirror, B=8, T=24, H=128):
    return sym.simple_bind(ctx=mx.cpu(), mirror=mirror,
                           data=(B, T, H), label=(B, T * H))


def _train_step(exe, data, label):
    exe.arg_dict["data"][:] = data
    exe.arg_dict["label"][:] = label
    exe.forward(is_train=True)
    exe.backward()
    return ([o.asnumpy() for o in exe.outputs],
            {k: v.asnumpy() for k, v in exe.grad_dict.items()
             if v is not None})


def test_mirror_matches_plain_numerics():
    sym = _deep_lstm_symbol()
    np.random.seed(3)
    B, T, H = 8, 24, 128
    data = np.random.uniform(-1, 1, (B, T, H)).astype("f")
    label = np.random.uniform(-1, 1, (B, T * H)).astype("f")
    params = None
    results = []
    for mirror in (False, True):
        exe = _bind(sym, mirror)
        if params is None:
            params = {k: np.random.uniform(-0.05, 0.05, v.shape).astype("f")
                      for k, v in exe.arg_dict.items()
                      if k not in ("data", "label")}
        for k, v in params.items():
            exe.arg_dict[k][:] = v
        results.append(_train_step(exe, data, label))
    (out_a, g_a), (out_b, g_b) = results
    np.testing.assert_allclose(out_a[0], out_b[0], rtol=1e-5, atol=1e-6)
    assert set(g_a) == set(g_b)
    for k in g_a:
        np.testing.assert_allclose(g_a[k], g_b[k], rtol=1e-4, atol=1e-5,
                                   err_msg=k)


def test_mirror_reduces_backward_memory():
    """Mirroring must shrink what backward stores: measure the residuals
    jax.vjp saves between forward and backward (the activation working
    set) via eval_shape — only segment boundaries survive under remat.
    (XLA-CPU's compiled temp_size does not model residual storage, so the
    gate is on the vjp residual pytree itself.)"""
    sym = _deep_lstm_symbol()
    res_bytes = {}
    for mirror in (False, True):
        exe = _bind(sym, mirror)
        arg_vals = exe._arg_vals()
        aux_vals = exe._aux_vals()
        watched = [nm for nm in exe.arg_names
                   if exe.grad_req.get(nm, "null") != "null"]
        assert watched
        w = {nm: arg_vals[nm] for nm in watched}
        rest = {nm: v for nm, v in arg_vals.items() if nm not in w}
        runner = exe._runner

        def f(wvals):
            outs, _ = runner({**rest, **wvals}, aux_vals, True,
                             jax.random.PRNGKey(0))
            return outs

        vjp_struct = jax.eval_shape(lambda ww: jax.vjp(f, ww)[1], w)
        leaves = jax.tree_util.tree_leaves(vjp_struct)
        res_bytes[mirror] = sum(
            int(np.prod(l.shape)) * l.dtype.itemsize for l in leaves)
    # params are always saved; activations must shrink enough to cut the
    # total residual set by a wide margin
    assert res_bytes[True] < 0.6 * res_bytes[False], res_bytes


def test_telemetry_per_op_attribution_matches_graph():
    """The telemetry tracer sees the same per-op structure named_scope
    bakes into HLO: one op_dispatch counter series per registered op and
    op.* spans carrying node names, nested under executor.compile."""
    from mxnet_tpu import telemetry as tm
    tm.disable()
    tm.reset()
    try:
        data = mx.sym.var("data")
        c = mx.sym.Convolution(data, kernel=(3, 3), num_filter=4,
                               name="tmconv")
        a = mx.sym.Activation(c, act_type="relu", name="tmrelu")
        out = mx.sym.FullyConnected(mx.sym.Flatten(a), num_hidden=3,
                                    name="tmfc")
        exe = out.simple_bind(ctx=mx.cpu(), data=(2, 3, 8, 8))
        tm.enable()
        exe.forward(is_train=False)
        exe.outputs[0].asnumpy()
        snap = tm.snapshot()
        for op in ("Convolution", "Activation", "Flatten",
                   "FullyConnected"):
            key = f'executor.op_dispatch{{op="{op}"}}'
            assert snap["counters"].get(key, 0) >= 1, (key,
                                                       snap["counters"])
        spans = tm.get_spans()
        node_names = {s.args.get("node") for s in spans
                      if s.name.startswith("op.")}
        assert {"tmconv", "tmrelu", "tmfc"} <= node_names
        # trace-time op spans nest under the compile-dispatch span
        op_parents = {s.parent for s in spans if s.name.startswith("op.")}
        assert "executor.compile" in op_parents
        assert snap["counters"].get("executor.jit_cache.miss", 0) == 1
    finally:
        tm.disable()
        tm.reset()


def test_named_scope_carries_node_names_into_hlo():
    """Every graph node executes under jax.named_scope(node.name), so the
    compiled HLO metadata carries Symbol names (profiler trace mapping)."""
    data = mx.sym.var("data")
    c = mx.sym.Convolution(data, kernel=(3, 3), num_filter=4,
                           name="myconv7")
    a = mx.sym.Activation(c, act_type="relu", name="myrelu9")
    f = mx.sym.Flatten(a, name="flat")
    out = mx.sym.FullyConnected(f, num_hidden=3, name="myfc11")
    exe = out.simple_bind(ctx=mx.cpu(), data=(2, 3, 8, 8))
    prog = exe._get_program("fwd_infer")
    txt = prog.lower(exe._arg_vals(), exe._aux_vals(),
                     jax.random.PRNGKey(0)).compile().as_text()
    for name in ("myconv7", "myrelu9", "myfc11"):
        assert name in txt, f"{name} missing from compiled HLO"
