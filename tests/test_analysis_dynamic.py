"""Dynamic-behavior static-analysis tests: host race lint (RC2xx),
program-cache-key completeness (CK3xx), determinism/replay audit
(DT4xx).

Three layers, matching the contract in docs/analysis.md:

* seeded fixtures — one minimal source per rule, each tripping exactly
  that rule, plus the suppression paths (``guarded-by`` / ``allow``);
* clean-corpus gates — the real tree must audit clean, the registry
  must be fully covered, and the rule ids must sit in the catalog;
* the runtime half of CK3xx — for EVERY registered knob, flip it and
  prove the program cache recompiles (and replays with zero compiles
  unflipped).  The static verifier says the knob is *in the key
  expression*; this battery says the key *actually moves*.
"""
import os
import sys
import textwrap

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.analysis import RULES, cachekey, determinism, racecheck
from mxnet_tpu.models.transformer import get_decode_symbol
from mxnet_tpu.test_utils import check_cache_key_knob

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mxlint_main():
    tools = os.path.join(REPO_ROOT, "tools")
    if tools not in sys.path:
        sys.path.insert(0, tools)
    import mxlint
    return mxlint.main


def _rules(findings):
    return sorted(f["rule"] for f in findings)


# ===================================================== RC2xx fixtures
RC201_SRC = textwrap.dedent("""
    import threading

    class Pump:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0
            self._t = None

        def start(self):
            self._t = threading.Thread(target=self._loop)
            self._t.start()

        def _loop(self):
            self._n += 1

        def poll(self):
            return self._n
""")

RC202_SRC = textwrap.dedent("""
    import threading

    class Pump:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()
            self._n = 0
            self._t = None

        def start(self):
            self._t = threading.Thread(target=self._loop)
            self._t.start()

        def _loop(self):
            with self._a:
                self._n += 1

        def poll(self):
            with self._b:
                return self._n
""")

RC203_SRC = textwrap.dedent("""
    import threading

    class Pump:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()
            self._n = 0
            self._t = None

        def start(self):
            self._t = threading.Thread(target=self._loop)
            self._t.start()

        def _loop(self):
            with self._a:
                with self._b:
                    self._n += 1

        def poll(self):
            with self._b:
                with self._a:
                    self._n -= 1
""")


def test_rc201_unguarded_cross_thread_write():
    res = racecheck.audit(None, sources={"fix.py": RC201_SRC})
    assert _rules(res["findings"]) == ["RC201"]
    (f,) = res["findings"]
    assert f["node"] == "Pump._n" and f["severity"] == "error"
    assert not res["ok"]


def test_rc202_inconsistent_guard():
    res = racecheck.audit(None, sources={"fix.py": RC202_SRC})
    assert "RC202" in _rules(res["findings"])
    assert not any(f["rule"] == "RC201" for f in res["findings"])


def test_rc203_lock_order_inversion():
    res = racecheck.audit(None, sources={"fix.py": RC203_SRC})
    assert "RC203" in _rules(res["findings"])


def test_rc_guarded_by_annotation_suppresses_and_records():
    src = RC201_SRC.replace("self._n += 1",
                            "self._n += 1  # mxlint: guarded-by(gil)")
    res = racecheck.audit(None, sources={"fix.py": src})
    assert res["ok"], _rules(res["findings"])
    assert len(res["annotated"]) >= 1
    assert any("gil" in str(a) for a in res["annotated"])


def test_rc_single_threaded_class_is_clean():
    src = textwrap.dedent("""
        class Plain:
            def __init__(self):
                self._n = 0

            def bump(self):
                self._n += 1
    """)
    res = racecheck.audit(None, sources={"fix.py": src})
    assert res["ok"], _rules(res["findings"])


# ===================================================== CK3xx fixtures
CK301_SCOPE_SRC = textwrap.dedent("""
    import os

    class Exec:
        def build(self):
            armed = os.environ.get("MXNET_TRAIN_HEALTH") == "1"
            return self.program_cache_key("fused", ("remat", "none"))
""")

CK301_SCOPE_KNOBS = (
    dict(name="health_armed", token="health",
         reads=("MXNET_TRAIN_HEALTH",), required=False),
    dict(name="remat_policy", token="remat", reads=(), required=False),
)


def test_ck301_scope_form_knob_read_but_not_keyed():
    """The PR-17-shape bug: a knob consulted while composing a key that
    never carries it."""
    res = cachekey.audit(sources={"executor.py": CK301_SCOPE_SRC},
                         knobs=CK301_SCOPE_KNOBS)
    assert _rules(res["findings"]) == ["CK301"]
    (f,) = res["findings"]
    assert f["node"] == "health_armed"
    assert not res["ok"]


def test_ck301_scope_form_clean_when_keyed():
    src = CK301_SCOPE_SRC.replace(
        '("remat", "none"))', '("remat", "none"), ("health", armed))')
    res = cachekey.audit(sources={"executor.py": src},
                         knobs=CK301_SCOPE_KNOBS)
    assert res["ok"], _rules(res["findings"])


CK301_CORPUS_SRC = textwrap.dedent("""
    class Exec:
        def build(self):
            return self.program_cache_key("fwd", ("remat", "none"))
""")


def test_ck301_corpus_form_required_knob_in_no_key():
    knobs = (dict(name="remat_policy", token="remat", reads=(),
                  required=True),
             dict(name="kernel_tier", token="ktier", reads=(),
                  required=True))
    res = cachekey.audit(sources={"executor.py": CK301_CORPUS_SRC},
                         knobs=knobs)
    assert _rules(res["findings"]) == ["CK301"]
    (f,) = res["findings"]
    assert f["target"] == "cachekey-registry"
    assert f["node"] == "kernel_tier"
    assert res["coverage"] == {"remat_policy": True, "kernel_tier": False}


def test_ck302_undeclared_key_element():
    src = textwrap.dedent("""
        class Exec:
            def build(self, x):
                return self.program_cache_key("fwd", ("mystery", x))
    """)
    knobs = (dict(name="remat_policy", token="remat", reads=(),
                  required=False),)
    res = cachekey.audit(sources={"executor.py": src}, knobs=knobs)
    assert _rules(res["findings"]) == ["CK302"]
    assert res["findings"][0]["node"] == "mystery"


CK303_KEY_SRC = textwrap.dedent("""
    def _key(op, shapes):
        return (("op", op), ("shape", tuple(shapes)))
""")


def test_ck303_autotune_key_missing_autotune_knob():
    knobs = (dict(name="remat_policy", token="remat", reads=(),
                  required=False, autotune=True),)
    res = cachekey.audit(sources={"kernel_tier.py": CK303_KEY_SRC},
                         knobs=knobs)
    assert _rules(res["findings"]) == ["CK303"]
    assert res["findings"][0]["node"] == "remat_policy"


def test_ck303_autotune_key_carries_non_autotune_knob():
    src = textwrap.dedent("""
        def _key(op, mode):
            return (("op", op), ("ktier", mode))
    """)
    knobs = (dict(name="kernel_tier", token="ktier", reads=(),
                  required=False, autotune=False),)
    res = cachekey.audit(sources={"kernel_tier.py": src}, knobs=knobs)
    assert _rules(res["findings"]) == ["CK303"]
    assert res["findings"][0]["node"] == "kernel_tier"


# ===================================================== DT4xx fixtures
DT401_SRC = textwrap.dedent("""
    import time

    def admit(queue):
        deadline = time.time() + 0.5
        return [q for q in queue if q.t < deadline]
""")


def test_dt401_wall_clock_off_the_seam():
    res = determinism.audit(sources={"serve/sched.py": DT401_SRC})
    assert _rules(res["findings"]) == ["DT401"]
    assert not res["ok"]


def test_dt401_clock_module_is_the_seam():
    res = determinism.audit(sources={"serve/clock.py": DT401_SRC})
    assert res["ok"], _rules(res["findings"])


def test_dt402_global_rng_in_graph_build():
    src = textwrap.dedent("""
        import numpy as np

        def init_graph(nodes):
            return np.random.rand(len(nodes))
    """)
    res = determinism.audit(sources={"executor.py": src})
    assert _rules(res["findings"]) == ["DT402"]


def test_dt403_set_iteration_orders_program_structure():
    src = textwrap.dedent("""
        def emit(parts):
            out = []
            for p in {"a", "b"} | set(parts):
                out.append(p)
            return out
    """)
    res = determinism.audit(sources={"executor.py": src})
    assert _rules(res["findings"]) == ["DT403"]


def test_dt403_sorted_set_is_clean():
    src = textwrap.dedent("""
        def emit(parts):
            out = []
            for p in sorted({"a", "b"} | set(parts)):
                out.append(p)
            return out
    """)
    res = determinism.audit(sources={"executor.py": src})
    assert res["ok"], _rules(res["findings"])


def test_dt_allow_annotation_suppresses_and_records():
    src = DT401_SRC.replace("time.time()",
                            "time.time()  # mxlint: allow(DT401)")
    res = determinism.audit(sources={"serve/sched.py": src})
    assert res["ok"], _rules(res["findings"])
    assert len(res["allowed"]) == 1


# ======================================= catalog + clean-corpus gates
def test_rule_catalog_has_dynamic_rules():
    for rid in ("RC201", "RC202", "RC203", "CK301", "CK302", "CK303",
                "DT401", "DT402", "DT403"):
        assert rid in RULES
        assert RULES[rid][0] == "error"


def test_race_audit_full_tree_clean():
    """Zero-FP gate over the whole package, not just the serve dirs —
    every remaining cross-thread write is either locked or carries a
    reviewed guarded-by claim."""
    res = racecheck.audit(REPO_ROOT, subdirs=("",))
    assert res["files_scanned"] > 50
    assert res["ok"], "\n".join(f["message"] for f in res["findings"])


def test_cachekey_audit_real_corpus_clean_and_fully_covered():
    res = cachekey.audit(REPO_ROOT)
    assert res["ok"], "\n".join(f["message"] for f in res["findings"])
    uncovered = [k for k, v in res["coverage"].items() if not v]
    assert not uncovered, uncovered
    assert set(res["coverage"]) == {k["name"] for k in cachekey.KNOBS}


def test_determinism_audit_real_corpus_clean():
    res = determinism.audit(REPO_ROOT)
    assert res["files_scanned"] >= 10
    assert res["ok"], "\n".join(f["message"] for f in res["findings"])


def test_mxlint_dynamic_audit_flags_exit_zero(capsys):
    main = _mxlint_main()
    assert main(["--race-audit"]) == 0
    assert main(["--cachekey-audit"]) == 0
    assert main(["--determinism-audit"]) == 0
    out = capsys.readouterr().out
    assert "race-audit" in out
    assert "cachekey-audit" in out
    assert "determinism-audit" in out


# ========================================== runtime knob-flip battery
BATCH, CLASSES, FEATS = 4, 3, 6


def _mlp(prefix, extra=False):
    data = mx.sym.var("data")
    fc1 = mx.sym.FullyConnected(data=data, num_hidden=8,
                                name=f"{prefix}_fc1")
    act = mx.sym.Activation(fc1, act_type="relu", name=f"{prefix}_relu1")
    if extra:
        act = mx.sym.Activation(act, act_type="tanh",
                                name=f"{prefix}_tanh")
    fc2 = mx.sym.FullyConnected(act, num_hidden=CLASSES,
                                name=f"{prefix}_fc2")
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def _init_args(prefix):
    rs = np.random.RandomState(1)
    return {
        f"{prefix}_fc1_weight": mx.nd.array(
            rs.randn(8, FEATS).astype(np.float32) * 0.1),
        f"{prefix}_fc1_bias": mx.nd.array(np.zeros(8, np.float32)),
        f"{prefix}_fc2_weight": mx.nd.array(
            rs.randn(CLASSES, 8).astype(np.float32) * 0.1),
        f"{prefix}_fc2_bias": mx.nd.array(np.zeros(CLASSES, np.float32)),
    }


def _fit_builder(prefix, cfg):
    """One-epoch tiny fit; every program-shaping input comes from
    ``cfg`` so a flip is one dict write."""
    def build():
        rs = np.random.RandomState(0)
        X = rs.rand(2 * BATCH, FEATS).astype(np.float32)
        y = rs.randint(0, CLASSES, (2 * BATCH,)).astype(np.float32)
        mx.random.seed(7)
        it = mx.io.NDArrayIter(X, y, batch_size=BATCH)
        ctxs = [mx.cpu(i) for i in range(cfg.get("n_ctx", 1))]
        mod = mx.mod.Module(_mlp(prefix, extra=cfg.get("extra", False)),
                            context=ctxs if len(ctxs) > 1 else ctxs[0],
                            compute_dtype=cfg.get("compute_dtype"),
                            fixed_param_names=cfg.get("fixed"))
        mod.fit(it, num_epoch=1,
                steps_per_dispatch=cfg.get("K", 1),
                zero_stage=cfg.get("zero", 0),
                health=cfg.get("health"),
                arg_params={k: v.copy()
                            for k, v in _init_args(prefix).items()},
                optimizer=cfg.get("opt", "sgd"),
                optimizer_params={"learning_rate": 0.05},
                allow_missing=False)
    return build


def _bind_builder(prefix, cfg):
    """Inference bind + forward; exercises the base-key knobs that
    don't need a train step."""
    def build():
        sym = _mlp(prefix)
        exe = sym.simple_bind(ctx=cfg.get("ctx") or mx.cpu(),
                              grad_req="null", data=(BATCH, FEATS))
        for k, v in _init_args(prefix).items():
            exe.arg_dict[k][:] = v
        exe.forward(is_train=False)
        for o in exe.outputs:
            o.wait_to_read()
    return build


def _two_head(prefix):
    data = mx.sym.var("data")
    fc1 = mx.sym.FullyConnected(data=data, num_hidden=8,
                                name=f"{prefix}_fc1")
    act = mx.sym.Activation(fc1, act_type="relu", name=f"{prefix}_relu1")
    h1 = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(act, num_hidden=CLASSES,
                              name=f"{prefix}_h1fc"), name="h1")
    h2 = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(act, num_hidden=CLASSES,
                              name=f"{prefix}_h2fc"), name="h2")
    return mx.sym.Group([h1, h2])


# decode symbols are memoized: rebuilding mutates the auto-naming
# counters, so only an identical *object* replays with zero compiles —
# exactly how the serving path holds one symbol per config
_DECODE_SYMS = {}


def _decode_sym(cfg):
    key = tuple(sorted((k, str(v)) for k, v in cfg.items()))
    if key not in _DECODE_SYMS:
        _DECODE_SYMS[key] = get_decode_symbol(
            vocab_size=16, d_model=8, n_layer=1, n_head=2, capacity=8,
            per_slot=cfg["per_slot"], step_len=cfg["step_len"],
            cache_dtype=cfg["cache_dtype"], name=cfg["name"])
    return _DECODE_SYMS[key]


def _decode_builder(cfg):
    def build():
        exe = _decode_sym(cfg).simple_bind(
            ctx=mx.cpu(), grad_req="null", data=(2, cfg["step_len"]))
        exe.forward(is_train=False)
        for o in exe.outputs:
            o.wait_to_read()
    return build


def _env_flip(var, val):
    def flip():
        os.environ[var] = val

    def restore():
        os.environ.pop(var, None)
    return flip, restore


def _set_flip(cfg, key, val):
    def flip():
        cfg[key] = val
    return flip


# name -> zero-arg factory returning (builder, flip, restore|None);
# keys must cover cachekey.KNOBS exactly (asserted below)
FLIPS = {}


def _case(name):
    def deco(fn):
        FLIPS[name] = fn
        return fn
    return deco


@_case("remat_policy")
def _flip_remat():
    f, r = _env_flip("MXNET_REMAT_POLICY", "dots")
    return _fit_builder("kf_remat", {}), f, r


@_case("kernel_tier")
def _flip_ktier():
    f, r = _env_flip("MXNET_KERNEL_TIER", "xla")
    return _fit_builder("kf_ktier", {}), f, r


@_case("keep_grads")
def _flip_keep_grads():
    f, r = _env_flip("MXNET_FUSED_KEEP_GRADS", "1")
    return _fit_builder("kf_kg", {}), f, r


@_case("health_armed")
def _flip_health():
    cfg = {}
    return _fit_builder("kf_health", cfg), _set_flip(cfg, "health", True), \
        None


@_case("scan_length")
def _flip_scan():
    cfg = {}
    return _fit_builder("kf_scan", cfg), _set_flip(cfg, "K", 2), None


@_case("optimizer_plan")
def _flip_opt():
    cfg = {}
    return _fit_builder("kf_opt", cfg), _set_flip(cfg, "opt", "adam"), None


@_case("compute_dtype")
def _flip_dtype():
    cfg = {}
    return _fit_builder("kf_dtype", cfg), \
        _set_flip(cfg, "compute_dtype", "bfloat16"), None


@_case("watched_params")
def _flip_watched():
    cfg = {}
    return _fit_builder("kf_watch", cfg), \
        _set_flip(cfg, "fixed", ["kf_watch_fc1_bias"]), None


@_case("comm_plan")
def _flip_comm():
    # two virtual CPU devices (conftest forces 8) so ZeRO actually arms
    cfg = {"n_ctx": 2}
    return _fit_builder("kf_zero", cfg), _set_flip(cfg, "zero", 1), None


@_case("symbol_signature")
def _flip_symbol():
    cfg = {}
    return _fit_builder("kf_sym", cfg), _set_flip(cfg, "extra", True), None


@_case("mesh_axes")
def _flip_mesh():
    cfg = {}
    return _bind_builder("kb_mesh", cfg), \
        _set_flip(cfg, "ctx", mx.cpu(1)), None


@_case("device_type")
def _flip_device_type():
    # cpu_pinned maps to the same jax device but is a distinct
    # Context type string — the cheapest honest device_type flip
    cfg = {}
    return _bind_builder("kb_devt", cfg), \
        _set_flip(cfg, "ctx", mx.Context("cpu_pinned", 0)), None


@_case("layout_opt")
def _flip_layout():
    f, r = _env_flip("MXNET_NHWC_LAYOUT", "0")
    return _bind_builder("kb_layout", {}), f, r


@_case("remat_segments")
def _flip_mirror():
    f, r = _env_flip("MXNET_BACKWARD_DO_MIRROR", "1")
    return _bind_builder("kb_mirror", {}), f, r


@_case("metric_pairs")
def _flip_metric_pairs():
    # the (output, label) pairing follows the iterator's provide_label
    # order, so the flip is the label-dict order
    cfg = {"order": ("h1_label", "h2_label")}

    def build():
        rs = np.random.RandomState(0)
        X = rs.rand(2 * BATCH, FEATS).astype(np.float32)
        y = rs.randint(0, CLASSES, (2 * BATCH,)).astype(np.float32)
        mx.random.seed(7)
        it = mx.io.NDArrayIter(X, {nm: y for nm in cfg["order"]},
                               batch_size=BATCH)
        mod = mx.mod.Module(_two_head("kf_met"), context=mx.cpu(),
                            label_names=["h1_label", "h2_label"])
        mod.fit(it, num_epoch=1, optimizer="sgd",
                optimizer_params={"learning_rate": 0.05})
    return build, _set_flip(cfg, "order", ("h2_label", "h1_label")), None


@_case("decode_per_slot")
def _flip_per_slot():
    cfg = {"per_slot": False, "step_len": 1, "cache_dtype": None,
           "name": "kd_ps"}
    return _decode_builder(cfg), _set_flip(cfg, "per_slot", True), None


@_case("decode_step_len")
def _flip_step_len():
    cfg = {"per_slot": True, "step_len": 1, "cache_dtype": None,
           "name": "kd_sl"}
    return _decode_builder(cfg), _set_flip(cfg, "step_len", 2), None


@_case("spec_k")
def _flip_spec_k():
    # the speculative verify window IS a step_len-K window graph
    cfg = {"per_slot": True, "step_len": 3, "cache_dtype": None,
           "name": "kd_sk"}
    return _decode_builder(cfg), _set_flip(cfg, "step_len", 4), None


@_case("cache_dtype")
def _flip_cache_dtype():
    cfg = {"per_slot": True, "step_len": 1, "cache_dtype": None,
           "name": "kd_cd"}
    return _decode_builder(cfg), \
        _set_flip(cfg, "cache_dtype", "bfloat16"), None


def test_flip_battery_covers_every_registered_knob():
    assert set(FLIPS) == {k["name"] for k in cachekey.KNOBS}


@pytest.mark.parametrize("knob", sorted(FLIPS))
def test_cache_key_knob_flip(knob):
    builder, flip, restore = FLIPS[knob]()
    check_cache_key_knob(builder, flip, restore, name=knob)
