"""NHWC layout-propagation pass (mxnet_tpu/ops/layout.py).

The pass must be numerically invisible: identical outputs/gradients with
``MXNET_NHWC_LAYOUT`` on and off, NCHW everywhere at the API surface, and
the NHWC domain must actually cover the conv trunk (transpose count).
Reference context: the reference is NCHW-native (convolution-inl.h); on
TPU the channel-minor layout is the performance-correct one, so the pass
is the TPU analog of cuDNN's internal NCHW kernels.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal


def _convnet():
    data = mx.sym.var("data")
    net = mx.sym.Convolution(data, num_filter=8, kernel=(3, 3), pad=(1, 1),
                             name="c1")
    net = mx.sym.BatchNorm(net, name="bn1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    branch = mx.sym.Convolution(net, num_filter=8, kernel=(3, 3),
                                pad=(1, 1), name="c2")
    net = branch + net                     # residual join inside the domain
    net = mx.sym.LRN(net, nsize=5)
    net = mx.sym.Concat(net, net, dim=1)
    parts = mx.sym.SliceChannel(net, num_outputs=2, axis=1)
    net = parts[0] * 1.0 + parts[1]
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc")
    return mx.sym.SoftmaxOutput(net, mx.sym.var("sm_label"), name="sm")


def _run(sym, train=True):
    mx.random.seed(0)
    exe = sym.simple_bind(mx.cpu(), data=(2, 3, 8, 8), sm_label=(2,))
    for nm, a in exe.arg_dict.items():
        if nm not in ("data", "sm_label"):
            a[:] = np.random.RandomState(
                abs(hash(nm)) % 2**31).uniform(-.2, .2, a.shape).astype(
                    np.float32)
    x = np.random.RandomState(1).rand(2, 3, 8, 8).astype(np.float32)
    y = np.array([0, 2], dtype=np.float32)
    exe.forward(is_train=train, data=mx.nd.array(x),
                sm_label=mx.nd.array(y))
    grads = {}
    if train:
        exe.backward()
        grads = {nm: g.asnumpy() for nm, g in exe.grad_dict.items()
                 if g is not None and nm != "data"}
    aux = {nm: a.asnumpy() for nm, a in exe.aux_dict.items()}
    return exe.outputs[0].asnumpy(), grads, aux


def test_layout_pass_numerically_invisible(monkeypatch):
    sym = _convnet()
    monkeypatch.setenv("MXNET_NHWC_LAYOUT", "0")
    out0, g0, aux0 = _run(sym)
    monkeypatch.setenv("MXNET_NHWC_LAYOUT", "1")
    out1, g1, aux1 = _run(sym)
    assert_almost_equal(out0, out1, rtol=1e-4, atol=1e-5)
    assert set(g0) == set(g1)
    for nm in g0:
        assert_almost_equal(g0[nm], g1[nm], rtol=1e-3, atol=1e-4)
    for nm in aux0:    # BN moving stats updated identically
        assert_almost_equal(aux0[nm], aux1[nm], rtol=1e-4, atol=1e-5)


def test_layout_pass_inference_path(monkeypatch):
    sym = _convnet()
    monkeypatch.setenv("MXNET_NHWC_LAYOUT", "0")
    out0, _, _ = _run(sym, train=False)
    monkeypatch.setenv("MXNET_NHWC_LAYOUT", "1")
    out1, _, _ = _run(sym, train=False)
    assert_almost_equal(out0, out1, rtol=1e-4, atol=1e-5)


def test_layout_domain_covers_trunk():
    """The NHWC domain must swallow the whole conv trunk: the traced
    program may transpose activation data only at the two boundaries
    (entry into the first conv, exit to Flatten) — everything else is
    the small per-conv OIHW->HWIO weight relayout XLA folds away."""
    import jax
    from mxnet_tpu.models import resnet
    sym = resnet.get_symbol(num_classes=10, num_layers=50,
                            image_shape="3,32,32")
    exe = sym.simple_bind(mx.cpu(), data=(2, 3, 32, 32),
                          softmax_label=(2,))
    jaxpr = jax.make_jaxpr(
        lambda a, x, r: exe._runner(a, x, True, r))(
            exe._arg_vals(), exe._aux_vals(), jax.random.PRNGKey(0))
    s = str(jaxpr)
    n_conv = s.count("conv_general_dilated")
    n_transpose = s.count("transpose[")
    assert n_conv >= 50
    # weight transposes scale with convs; activation transposes must not
    assert n_transpose <= n_conv + 6, (n_conv, n_transpose)


def test_layout_pass_grouped_conv_and_prelu(monkeypatch):
    data = mx.sym.var("data")
    net = mx.sym.Convolution(data, num_filter=4, kernel=(1, 1), name="c0")
    net = mx.sym.Convolution(net, num_filter=8, kernel=(3, 3), pad=(1, 1),
                             num_group=2, name="c1")
    net = mx.sym.LeakyReLU(net, act_type="prelu", name="pr")
    net = mx.sym.Pooling(net, global_pool=True, pool_type="avg",
                         kernel=(1, 1))
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=3, name="fc")
    sym = mx.sym.SoftmaxOutput(net, mx.sym.var("sm_label"), name="sm")
    monkeypatch.setenv("MXNET_NHWC_LAYOUT", "0")
    out0, g0, _ = _run(sym)
    monkeypatch.setenv("MXNET_NHWC_LAYOUT", "1")
    out1, g1, _ = _run(sym)
    assert_almost_equal(out0, out1, rtol=1e-4, atol=1e-5)
    for nm in g0:
        assert_almost_equal(g0[nm], g1[nm], rtol=1e-3, atol=1e-4)
