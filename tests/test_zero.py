"""ZeRO-1 sharded optimizer updates + in-program reduce-scatter.

The fused/scan train step's reduce-scatter comm plan (ISSUE 4 tentpole:
``Module.fit(zero_stage=1)`` / ``MXNET_ZERO_STAGE``) must be a pure
re-layout of the computation: these tests pin (a) bit-for-bit parameter
and optimizer-state parity with the replicated (all-reduce) plan for
SGD+momentum and Adam on a 2-device mesh, (b) equivalence of the K=4
scan under the sharded plan — dropout rng included, since both plans
share the fused rng chain, (c) parity against the post-hoc kvstore
push/pull arrangement, (d) the N-fold optimizer-state sharding, and
(e) checkpoint portability between the sharded and replicated layouts.
"""
import numpy as np
import pytest

import jax

import mxnet_tpu as mx

pytestmark = pytest.mark.skipif(
    len(jax.devices("cpu")) < 2, reason="needs >=2 virtual cpu devices")

BATCH = 4
N_BATCHES = 8
CLASSES = 3
FEATS = 6


def _mlp(dropout=0.0):
    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data=data, num_hidden=8, name="fc1")
    act = mx.sym.Activation(fc, act_type="relu")
    if dropout:
        act = mx.sym.Dropout(act, p=dropout)
    fc2 = mx.sym.FullyConnected(act, num_hidden=CLASSES, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def _data():
    rs = np.random.RandomState(0)
    X = rs.rand(N_BATCHES * BATCH, FEATS).astype(np.float32)
    y = rs.randint(0, CLASSES, (N_BATCHES * BATCH,)).astype(np.float32)
    return X, y


def _init_args():
    rs = np.random.RandomState(1)
    return {
        "fc1_weight": mx.nd.array(rs.randn(8, FEATS).astype(np.float32)
                                  * 0.1),
        "fc1_bias": mx.nd.array(np.zeros(8, np.float32)),
        "fc2_weight": mx.nd.array(rs.randn(CLASSES, 8).astype(np.float32)
                                  * 0.1),
        "fc2_bias": mx.nd.array(np.zeros(CLASSES, np.float32)),
    }


def _fit(zero_stage, optimizer="sgd", K=1, dropout=0.0, n_dev=2,
         kvstore="local", num_epoch=1):
    """One fit; returns (params, host-format optimizer states, per-batch
    metric trajectory, module)."""
    X, y = _data()
    mx.random.seed(7)
    it = mx.io.NDArrayIter(X, y, batch_size=BATCH)
    mod = mx.mod.Module(_mlp(dropout),
                        context=[mx.cpu(i) for i in range(n_dev)])
    accs = []

    def cb(param):
        accs.append(param.eval_metric.get()[1])

    opt_params = (("learning_rate", 0.1), ("momentum", 0.9)) \
        if optimizer == "sgd" else (("learning_rate", 0.01),)
    mod.fit(it, num_epoch=num_epoch, zero_stage=zero_stage,
            steps_per_dispatch=K, kvstore=kvstore, optimizer=optimizer,
            batch_end_callback=cb,
            arg_params={k: v.copy() for k, v in _init_args().items()},
            optimizer_params=opt_params)
    args, _ = mod.get_params()
    params = {k: v.asnumpy() for k, v in args.items()}
    if getattr(mod._exec_group, "_fused_prog", None) is not None \
            and mod._fused_armed:
        states = mod._exec_group.export_fused_states()
    else:
        states = None
    return params, states, accs, mod


@pytest.mark.parametrize("optimizer", ["sgd", "adam"])
def test_zero1_update_bit_for_bit(optimizer):
    """Given identical (w, grad, state), the sharded update IS the
    replicated update, bit for bit: the same elementwise scalar ops run
    on the same values, only on 1/N-shard layouts."""
    import jax.numpy as jnp
    from jax.sharding import Mesh
    from mxnet_tpu.parallel.zero import ZeroPlan
    opt = mx.optimizer.create(
        optimizer, learning_rate=0.05, momentum=0.9, wd=1e-4) \
        if optimizer == "sgd" else mx.optimizer.create(
            optimizer, learning_rate=0.05, wd=1e-4)
    init_state, update = opt.fused_plan()
    mesh = Mesh(np.array(jax.devices("cpu")[:2]), ("data",))
    plan = ZeroPlan(mesh, "data")
    rs = np.random.RandomState(0)
    for shape in [(7,), (8, 6), (3, 5, 2)]:
        w = jnp.asarray(rs.randn(*shape).astype(np.float32))
        g = jnp.asarray(rs.randn(*shape).astype(np.float32))
        s_full = init_state(w)
        s_shard = plan.init_state(init_state, w)
        lr, wd = jnp.float32(0.05), jnp.float32(1e-4)

        ref_w, ref_s = jax.jit(update)(w, g, s_full, lr, wd)
        new_w, new_s = jax.jit(
            lambda w, g, s: plan.apply(update, w, g, s, lr, wd))(
                w, g, s_shard)
        np.testing.assert_array_equal(np.asarray(ref_w),
                                      np.asarray(new_w), err_msg=shape)
        for l_ref, l_new in zip(jax.tree.leaves(ref_s),
                                jax.tree.leaves(new_s)):
            np.testing.assert_array_equal(
                np.asarray(l_ref),
                np.asarray(plan._unflat(jnp.asarray(l_new), shape)),
                err_msg=shape)


@pytest.mark.parametrize("optimizer", ["sgd", "adam"])
def test_zero1_fit_matches_replicated(optimizer):
    """End-to-end fit under the sharded plan tracks the replicated plan
    to float ulps (XLA may fuse the backward differently around the
    reduce-scatter; the update itself is exact — see the bit-for-bit
    test above) and the per-batch metric trajectory is identical."""
    p0, s0, a0, _ = _fit(0, optimizer)
    p1, s1, a1, mod1 = _fit(1, optimizer)
    assert mod1._exec_group._zero_plan is not None
    for k in p0:
        np.testing.assert_allclose(p0[k], p1[k], rtol=1e-6, atol=1e-6,
                                   err_msg=k)
    for k in s0:
        leaves0 = jax.tree.leaves(s0[k])
        leaves1 = jax.tree.leaves(s1[k])
        assert len(leaves0) == len(leaves1)
        for l0, l1 in zip(leaves0, leaves1):
            np.testing.assert_allclose(np.asarray(l0), np.asarray(l1),
                                       rtol=1e-5, atol=1e-6, err_msg=k)
    np.testing.assert_allclose(a0, a1, rtol=1e-12)


def test_zero1_scan_k4_with_dropout():
    """K=4 scan under the sharded plan == K=1 sharded == K=1 replicated,
    dropout rng included (all fused arrangements share one rng chain)."""
    p_ar, _, a_ar, _ = _fit(0, dropout=0.3)
    p_rs, _, a_rs, _ = _fit(1, dropout=0.3)
    p_rs4, _, a_rs4, mod4 = _fit(1, K=4, dropout=0.3)
    assert mod4._exec_group._scan_K == 4
    assert mod4._exec_group._zero_plan is not None
    for k in p_ar:
        np.testing.assert_allclose(p_ar[k], p_rs[k], rtol=1e-6, atol=1e-6,
                                   err_msg=k)
        np.testing.assert_allclose(p_rs[k], p_rs4[k], rtol=2e-5,
                                   atol=2e-6, err_msg=k)
    np.testing.assert_allclose(a_ar, a_rs, rtol=1e-12)
    np.testing.assert_allclose(a_rs, a_rs4, rtol=1e-12)


def test_zero1_matches_posthoc_push_pull():
    """The in-program reduce-scatter plan must reproduce the post-hoc
    kvstore push/pull arrangement (update_on_kvstore: grads pushed to
    the store, updated weights pulled back) — params, optimizer state
    and the per-batch metric trajectory. No dropout: the staged path
    draws its rng per dispatch, the fused path chains on device."""
    p_kv, _, a_kv, mod_kv = _fit(0, kvstore="device", num_epoch=2)
    assert not mod_kv._fused_armed           # post-hoc arrangement ran
    assert mod_kv._update_on_kvstore
    p_rs, s_rs, a_rs, _ = _fit(1, num_epoch=2)
    for k in p_kv:
        np.testing.assert_allclose(p_kv[k], p_rs[k], rtol=2e-5, atol=2e-6,
                                   err_msg=k)
    np.testing.assert_allclose(a_kv, a_rs, rtol=1e-6)
    # optimizer-state parity: the store updater's momentum per index vs
    # the exported (param-shaped) fused state per name
    kv_states = mod_kv._kvstore._updater.states
    names = mod_kv._param_names
    for i, nm in enumerate(names):
        if nm not in s_rs or kv_states.get(i) is None:
            continue
        np.testing.assert_allclose(kv_states[i].asnumpy(),
                                   np.asarray(jax.tree.leaves(s_rs[nm])[0]),
                                   rtol=2e-5, atol=2e-6, err_msg=nm)


def test_zero1_state_is_sharded():
    """Each device materializes only its 1/N slice of every optimizer
    state — the ZeRO-1 memory cut."""
    _, _, _, mod = _fit(1, optimizer="adam")
    plan = mod._exec_group._zero_plan
    assert plan is not None and plan.n == 2
    for nm, st in mod._exec_group._fused_states.items():
        for leaf in jax.tree.leaves(st):
            assert leaf.shape[0] == plan.n, (nm, leaf.shape)
            # one addressable shard per device, 1/N of the elements each
            shards = leaf.addressable_shards
            assert len(shards) == plan.n
            for sh in shards:
                assert sh.data.shape[0] == 1, (nm, sh.data.shape)


def test_zero1_checkpoint_roundtrip(tmp_path):
    """States saved under the sharded plan load into a replicated-plan
    module (and back) — checkpoints are layout-independent."""
    fname = str(tmp_path / "zero.states")
    _, s_rs, _, mod_rs = _fit(1)
    mod_rs.save_optimizer_states(fname)
    # load into a replicated-plan module: states must land exactly
    _, _, _, mod_ar = _fit(0)
    mod_ar.load_optimizer_states(fname)
    s_ar = mod_ar._exec_group.export_fused_states()
    for nm in s_rs:
        for l_rs, l_ar in zip(jax.tree.leaves(s_rs[nm]),
                              jax.tree.leaves(s_ar[nm])):
            np.testing.assert_array_equal(np.asarray(l_rs),
                                          np.asarray(l_ar), err_msg=nm)
    # and back into a sharded-plan module
    _, _, _, mod_rs2 = _fit(1)
    mod_rs2.load_optimizer_states(fname)
    s_rs2 = mod_rs2._exec_group.export_fused_states()
    for nm in s_rs:
        for l_a, l_b in zip(jax.tree.leaves(s_rs[nm]),
                            jax.tree.leaves(s_rs2[nm])):
            np.testing.assert_array_equal(np.asarray(l_a),
                                          np.asarray(l_b), err_msg=nm)


def test_zero_env_var_default(monkeypatch):
    """MXNET_ZERO_STAGE=1 arms the sharded plan without the kwarg."""
    monkeypatch.setenv("MXNET_ZERO_STAGE", "1")
    _, _, _, mod = _fit(None)
    assert mod._exec_group._zero_plan is not None


def test_zero_single_device_falls_back():
    """zero_stage=1 on one device keeps the replicated plan (no mesh)."""
    _, _, _, mod = _fit(1, n_dev=1)
    assert mod._fused_armed
    assert mod._exec_group._zero_plan is None


def test_zero_program_cache_keys_differ():
    """The comm-plan token keys the program cache: an rs-plan program
    can never false-hit an ar-plan trace of the same symbol."""
    _, _, _, mod_ar = _fit(0)
    _, _, _, mod_rs = _fit(1)
    k_ar = mod_ar._exec_group._fused_cache_key
    k_rs = mod_rs._exec_group._fused_cache_key
    assert k_ar is not None and k_rs is not None
    assert k_ar != k_rs
    assert ("comm", "ar") in k_ar and ("comm", "rs") in k_rs
