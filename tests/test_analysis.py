"""Static-analysis (graph verifier & hazard linter) tests.

Three seeded-hazard fixtures — a use-after-donation fused plan, a
nondeterministic bucket order, a cache-churn attr — each tripping
exactly one rule, plus zero-false-positive gates over the bundled
model zoo and the ZeRO/scan/bucketed configurations, the GV/HS rule
set, bind-time warn/raise surfaces, telemetry mirroring, suppression,
and the registration-time infer-signature validation.
"""
import json
import logging

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.analysis import (AnalysisContext, RULES, lint_json,
                                lint_module, lint_symbol, run_passes)
from mxnet_tpu.kvstore_sched import BucketScheduler
from mxnet_tpu.ops.registry import OpDef
from mxnet_tpu.program_cache import attr_cache_stable


def _two_fc():
    """Two same-shape FC layers: aliasing one weight cell onto the
    other keeps every shape consistent (the donation fixture must trip
    DA201 alone, not a shape rule)."""
    d = mx.sym.var("data")
    h = mx.sym.FullyConnected(d, num_hidden=16, name="fc1")
    h = mx.sym.Activation(h, act_type="relu", name="r1")
    h = mx.sym.FullyConnected(h, num_hidden=16, name="fc2")
    return mx.sym.SoftmaxOutput(h, name="softmax")


def _fused_module():
    mod = mx.mod.Module(_two_fc(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (8, 16))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params()
    mod.init_optimizer(kvstore=None)
    assert mod._fused_armed
    return mod


def _mlp():
    d = mx.sym.var("data")
    h = mx.sym.FullyConnected(d, num_hidden=8, name="fc1")
    h = mx.sym.Activation(h, act_type="relu", name="r1")
    h = mx.sym.FullyConnected(h, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(h, name="softmax")


# ------------------------------------------------------ seeded fixtures
def test_fixture_use_after_donation():
    """Aliasing a second arg name onto a donated param cell trips DA201
    and nothing else."""
    mod = _fused_module()
    exe = mod._exec_group.executor
    i1 = exe.arg_names.index("fc1_weight")
    i2 = exe.arg_names.index("fc2_weight")
    exe.arg_arrays[i2] = exe.arg_arrays[i1]
    report = lint_module(mod)
    assert report.rules == {"DA201"}
    assert len(report) == 1
    d = report.errors[0]
    assert "fc1_weight" in d.message and "fc2_weight" in d.message


def test_fixture_nondeterministic_bucket_order():
    """Equal-priority keys staged from two push calls in one window
    trip CO301 (multiworker audit) and nothing else."""
    sched = BucketScheduler(lambda x: x, lambda k, c, v: None,
                            lambda: 1 << 30)
    sched.note_push_call()
    sched.stage(3, None, np.zeros(4, np.float32), priority=0)
    sched.note_push_call()
    sched.stage(5, None, np.zeros(4, np.float32), priority=0)
    report = run_passes(AnalysisContext(sched=sched,
                                        assume_multiworker=True))
    assert report.rules == {"CO301"}
    assert len(report) == 1
    # same plan is fine on a single worker (no divergence possible)
    assert not len(run_passes(AnalysisContext(sched=sched)))


def test_fixture_cache_churn_attr():
    """An array-valued op attr trips RC401 and nothing else."""
    net = _mlp()
    node = net._outputs[0][0]
    node.attrs["debug_buffer"] = np.arange(3)
    report = lint_symbol(net, shapes={"data": (2, 8)})
    assert report.rules == {"RC401"}
    assert len(report) == 1
    assert "debug_buffer" in report.warnings[0].message


# -------------------------------------------------- zero-false-positive
MODEL_SHAPES = [
    ("mlp", lambda m: m.mlp.get_symbol(10), {"data": (8, 784)}),
    ("lenet", lambda m: m.lenet.get_symbol(10), {"data": (8, 1, 28, 28)}),
    ("alexnet", lambda m: m.alexnet.get_symbol(10),
     {"data": (2, 3, 224, 224)}),
    ("vgg16", lambda m: m.vgg.get_symbol(10, 16),
     {"data": (1, 3, 224, 224)}),
    ("resnet20", lambda m: m.resnet.get_symbol(10, 20, "3,32,32"),
     {"data": (4, 3, 32, 32)}),
    ("inception_bn", lambda m: m.inception_bn.get_symbol(10),
     {"data": (1, 3, 224, 224)}),
    ("inception_v3", lambda m: m.inception_v3.get_symbol(10),
     {"data": (1, 3, 299, 299)}),
]


@pytest.mark.parametrize("name,build,shapes", MODEL_SHAPES,
                         ids=[m[0] for m in MODEL_SHAPES])
def test_bundled_models_lint_clean(name, build, shapes):
    from mxnet_tpu import models
    report = lint_symbol(build(models), shapes=shapes)
    assert not len(report), f"{name}: {report.format()}"


def test_fused_module_lint_clean():
    """The plain fused (replicated) arrangement has zero findings."""
    report = lint_module(_fused_module())
    assert not len(report), report.format()


def test_zero_scan_config_lint_clean():
    """The ZeRO-1 + K-step-scan arrangement on the 8-device mesh —
    the config test_zero/test_scan_fit exercise — has zero findings."""
    X = np.random.rand(32, 8).astype(np.float32)
    Y = np.zeros(32, np.float32)
    it = mx.io.NDArrayIter(X, Y, batch_size=8, label_name="softmax_label")
    mod = mx.mod.Module(_mlp(), context=[mx.cpu(i) for i in range(8)])
    mod.fit(it, num_epoch=1, zero_stage=1, steps_per_dispatch=2,
            kvstore=None)
    assert mod._exec_group._zero_plan is not None
    report = lint_module(mod)
    assert not len(report), report.format()


def test_kvstore_bucket_plan_lint_clean():
    """Module.update's push contract — ONE call, distinct priorities —
    audits clean even under the multiworker assumption."""
    kv = mx.kv.create("dist_sync")
    try:
        kv.init(0, mx.nd.zeros((4,)))
        kv.init(1, mx.nd.zeros((4,)))
        kv.push([1, 0], [mx.nd.ones((4,)), mx.nd.ones((4,))],
                priority=[1, 0])
        kv.pull([0, 1], [mx.nd.zeros((4,)), mx.nd.zeros((4,))])
        report = run_passes(AnalysisContext(kvstore=kv, sched=kv._sched,
                                            assume_multiworker=True))
        assert not len(report), report.format()
    finally:
        kv.close()


# -------------------------------------------------------- graph verifier
def test_gv_duplicate_variable():
    a = mx.sym.var("x")
    b = mx.sym.var("x")
    report = lint_symbol(a + b)
    assert report.rules == {"GV103"}


def test_gv_duplicate_node_name():
    d = mx.sym.var("data")
    h = mx.sym.FullyConnected(d, weight=mx.sym.var("w1"),
                              bias=mx.sym.var("b1"), num_hidden=4,
                              name="fc")
    h = mx.sym.FullyConnected(h, weight=mx.sym.var("w2"),
                              bias=mx.sym.var("b2"), num_hidden=4,
                              name="fc")
    report = lint_symbol(h, shapes={"data": (2, 4)})
    assert report.rules == {"GV104"}


def test_gv_inference_conflict_is_error():
    a = mx.sym.var("a")
    b = mx.sym.var("b")
    report = lint_symbol(a + b, shapes={"a": (2, 3), "b": (4, 5)})
    assert report.rules == {"GV101"}
    msg = report.errors[0].message
    assert "_plus" in msg and "(2, 3)" in msg and "(4, 5)" in msg


def test_gv_stall_without_infer_shape():
    """An op with neither infer_shape nor shape_passthrough stalls on a
    partial input shape -> GV107 names the op."""
    d = mx.sym.var("data", shape=(0, 5))     # batch unknown
    net = mx.sym.Flatten(d)
    report = lint_symbol(net)
    assert "GV107" in report.rules
    assert any(f.op == "Flatten" for f in report)


def test_gv_shape_passthrough_flag_infers_and_silences():
    """softmax declares shape_passthrough: partial shapes flow through
    it (forward and backward) and GV107 stays quiet."""
    d = mx.sym.var("data", shape=(0, 7))
    net = mx.sym.softmax(d)
    report = lint_symbol(net)
    assert "GV107" not in report.rules
    # and the flag actually propagates shapes both ways
    _, outs, _ = net.infer_shape_partial(data=(4, 7))
    assert outs == [(4, 7)]


def test_gv_dtype_conflict():
    d = mx.sym.var("data", dtype="float16")
    net = mx.sym.FullyConnected(d, num_hidden=4, name="fc")
    exe = net.simple_bind(ctx=mx.cpu(), data=(2, 8), validate=None)
    from mxnet_tpu.analysis import lint_executor
    report = lint_executor(exe)
    assert "GV105" in report.rules


def test_json_dead_node_and_dangling_input():
    doc = {"nodes": [
        {"op": "null", "name": "a", "inputs": []},
        {"op": "null", "name": "dead", "inputs": []},
        {"op": "_copy", "name": "c", "inputs": [[0, 0, 0]]}],
        "arg_nodes": [0, 1], "heads": [[2, 0, 0]]}
    report = lint_json(json.dumps(doc))
    assert "GV108" in report.rules
    assert any(f.node == "dead" for f in report)

    doc2 = {"nodes": [{"op": "_copy", "name": "c",
                       "inputs": [[5, 0, 0]]}],
            "arg_nodes": [], "heads": [[0, 0, 0]]}
    report2 = lint_json(json.dumps(doc2))
    assert "GV106" in report2.rules


def test_saved_symbol_roundtrip_lints_clean(tmp_path):
    net = _mlp()
    path = tmp_path / "mlp-symbol.json"
    net.save(str(path))
    report = lint_json(path.read_text(), shapes={"data": (8, 8)})
    assert not len(report), report.format()


# ------------------------------------------------- donation / collective
def test_da_donated_param_as_label_input():
    mod = _fused_module()
    g = mod._exec_group
    g.label_names = list(g.label_names) + ["fc1_weight"]
    report = lint_module(mod)
    assert report.rules == {"DA203"}


def test_da_shared_cells_with_fused_plan():
    mod = _fused_module()
    mod._exec_group._shared_param_names = {"fc1_weight"}
    report = lint_module(mod)
    assert report.rules == {"DA202"}


def test_da_bucket_buffer_alias():
    sched = BucketScheduler(lambda x: x, lambda k, c, v: None,
                            lambda: 1 << 30)
    buf = np.zeros(4, np.float32)
    sched.note_push_call()
    sched.stage(0, None, buf, priority=1)
    sched.stage(1, None, buf, priority=0)
    report = run_passes(AnalysisContext(sched=sched))
    assert report.rules == {"DA204"}


def test_co_watched_order_mismatch():
    mod = _fused_module()
    mod._exec_group._fused_watched = \
        list(reversed(mod._exec_group._fused_watched))
    report = lint_module(mod)
    assert report.rules == {"CO303"}


def test_co_zero_plan_with_dist_kvstore():
    mod = _fused_module()
    kv = mx.kv.create("dist_sync")
    try:
        from mxnet_tpu.parallel.zero import ZeroPlan
        mod._exec_group._zero_plan = ZeroPlan.__new__(ZeroPlan)
        mod._exec_group._zero_plan.axis = "data"
        mod._exec_group._zero_plan.n = 8
        mod._kvstore = kv
        report = lint_module(mod)
        assert "CO302" in report.rules
    finally:
        mod._kvstore = None
        kv.close()


# ------------------------------------------------------------- host sync
def test_hs_naive_engine(monkeypatch):
    monkeypatch.setenv("MXNET_ENGINE_TYPE", "NaiveEngine")
    net = _mlp()
    exe = net.simple_bind(ctx=mx.cpu(), data=(2, 8), validate=None)
    from mxnet_tpu.analysis import lint_executor
    report = lint_executor(exe)
    assert report.rules == {"HS501"}


def test_hs_monitor_tap_is_info():
    net = _mlp()
    exe = net.simple_bind(ctx=mx.cpu(), data=(2, 8), validate=None)
    exe.set_monitor_callback(lambda name, arr: None)
    from mxnet_tpu.analysis import lint_executor
    report = lint_executor(exe)
    assert report.rules == {"HS502"}
    assert report.infos and not report.errors and not report.warnings


# ------------------------------------------------------- retrace / cache
def test_rc_uncacheable_binding():
    net = _mlp()
    exe = net.simple_bind(ctx=mx.cpu(), data=(2, 8), validate=None)
    exe._prog_cache_base = None
    from mxnet_tpu.analysis import lint_executor
    report = lint_executor(exe)
    assert report.rules == {"RC402"}


def test_attr_cache_stable_predicate():
    assert attr_cache_stable(3)[0]
    assert attr_cache_stable("relu")[0]
    assert attr_cache_stable((1, 2, 3))[0]
    assert attr_cache_stable(1.5)[0]
    assert not attr_cache_stable(float("nan"))[0]
    assert not attr_cache_stable(np.arange(2))[0]
    assert not attr_cache_stable(lambda x: x)[0]
    assert not attr_cache_stable(object())[0]


# ------------------------------------------------------ surfaces / modes
def test_bind_validate_raise_mode():
    a = mx.sym.var("a")
    b = mx.sym.var("b")
    bad = a + b
    with pytest.raises(mx.MXNetError, match="GV101"):
        bad.bind(mx.cpu(), args={"a": mx.nd.ones((2, 3)),
                                 "b": mx.nd.ones((4, 5))},
                 validate="raise")


def test_bind_validate_warn_mode_logs(caplog):
    a = mx.sym.var("a")
    b = mx.sym.var("b")
    bad = a + b
    with caplog.at_level(logging.WARNING, logger="mxnet_tpu.analysis"):
        exe = bad.bind(mx.cpu(), args={"a": mx.nd.ones((2, 3)),
                                       "b": mx.nd.ones((4, 5))},
                       validate="warn")
    assert exe is not None          # warn mode never blocks the bind
    assert any("GV101" in rec.message for rec in caplog.records)


def test_env_validate_mode(monkeypatch):
    monkeypatch.setenv("MXNET_GRAPH_VALIDATE", "raise")
    a = mx.sym.var("a")
    b = mx.sym.var("b")
    with pytest.raises(mx.MXNetError, match="GV101"):
        (a + b).bind(mx.cpu(), args={"a": mx.nd.ones((2, 3)),
                                     "b": mx.nd.ones((4, 5))})
    # per-call override beats the env
    exe = (a + b).bind(mx.cpu(), args={"a": mx.nd.ones((2, 3)),
                                       "b": mx.nd.ones((4, 5))},
                       validate="warn")
    assert exe is not None


def test_lint_disable_suppression(monkeypatch):
    net = _mlp()
    node = net._outputs[0][0]
    node.attrs["debug_buffer"] = np.arange(3)
    monkeypatch.setenv("MXNET_LINT_DISABLE", "RC401")
    assert not len(lint_symbol(net, shapes={"data": (2, 8)}))
    monkeypatch.setenv("MXNET_LINT_DISABLE", "retrace_churn")
    assert not len(lint_symbol(net, shapes={"data": (2, 8)}))
    monkeypatch.setenv("MXNET_LINT_DISABLE", "all")
    assert not len(lint_symbol(net, shapes={"data": (2, 8)}))
    monkeypatch.delenv("MXNET_LINT_DISABLE")
    assert len(lint_symbol(net, shapes={"data": (2, 8)})) == 1


def test_findings_mirror_into_telemetry():
    from mxnet_tpu.telemetry import flightrec, metrics
    mod = _fused_module()
    exe = mod._exec_group.executor
    i1 = exe.arg_names.index("fc1_weight")
    i2 = exe.arg_names.index("fc2_weight")
    exe.arg_arrays[i2] = exe.arg_arrays[i1]
    before = metrics.get_metric("analysis.lint.findings", rule="DA201",
                                severity="error")
    base = before.value if before else 0
    flightrec.clear()
    lint_module(mod)
    after = metrics.get_metric("analysis.lint.findings", rule="DA201",
                               severity="error")
    assert after is not None and after.value == base + 1
    recs = [r for r in flightrec.get_records()
            if r.get("kind") == "lint.finding"]
    assert recs and recs[-1]["rule"] == "DA201"


def test_diagnose_renders_lint_findings(tmp_path):
    """tools/diagnose.py shows lint findings in a crash report."""
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    try:
        import diagnose
    finally:
        sys.path.pop(0)
    report = {
        "type": "crash_report", "time": "t", "pid": 1, "where": "bind",
        "ring": [{"kind": "lint.finding", "ts_us": 1, "rule": "DA201",
                  "severity": "error", "node": "fc1_weight",
                  "message": "one buffer is bound twice"}],
        "metrics": {"counters":
                    {'analysis.lint.findings{rule="DA201",'
                     'severity="error"}': 1}},
    }
    path = tmp_path / "crash.json"
    path.write_text(json.dumps(report))
    text = diagnose.render_file(str(path))
    assert "lint findings" in text and "DA201" in text


def test_rule_catalog_consistency():
    """Every rule id used in this file exists; severities are valid."""
    for rule, (sev, title) in RULES.items():
        assert sev in ("info", "warning", "error")
        assert title


# ------------------------------------------------------------ mxlint CLI
def _mxlint_main():
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    try:
        import mxlint
    finally:
        sys.path.pop(0)
    return mxlint.main


def test_mxlint_check_gate(capsys):
    """The CI gate: every bundled model + the two example graphs lint
    clean (exit 0). Runs mxlint in-process so tier-1 pays no second
    interpreter/jax start-up."""
    main = _mxlint_main()
    assert main(["--check"]) == 0
    out = capsys.readouterr().out
    assert "models/resnet20" in out and "examples/dcgan.generator" in out
    assert "0 error(s)" in out


def test_mxlint_json_file_exit_codes(tmp_path, capsys):
    main = _mxlint_main()
    good = _mlp()
    good_path = tmp_path / "good-symbol.json"
    good.save(str(good_path))
    assert main([str(good_path), "--shape", "data=8,8"]) == 0

    bad = {"nodes": [{"op": "_copy", "name": "c",
                      "inputs": [[5, 0, 0]]}],
           "arg_nodes": [], "heads": [[0, 0, 0]]}
    bad_path = tmp_path / "bad-symbol.json"
    bad_path.write_text(json.dumps(bad))
    assert main([str(bad_path)]) == 1          # nonzero on errors
    out = capsys.readouterr().out
    assert "GV106" in out

    # warnings pass by default, fail under --strict
    warn = {"nodes": [
        {"op": "null", "name": "a", "inputs": []},
        {"op": "null", "name": "dead", "inputs": []},
        {"op": "_copy", "name": "c", "inputs": [[0, 0, 0]]}],
        "arg_nodes": [0, 1], "heads": [[2, 0, 0]]}
    warn_path = tmp_path / "warn-symbol.json"
    warn_path.write_text(json.dumps(warn))
    assert main([str(warn_path)]) == 0
    assert main([str(warn_path), "--strict"]) == 1
    assert main([]) == 2                        # nothing to lint


def test_mxlint_rules_listing(capsys):
    main = _mxlint_main()
    assert main(["--rules"]) == 0
    out = capsys.readouterr().out
    for rule in RULES:
        assert rule in out


# -------------------------------- registration-time infer validation (S2)
def test_register_validates_infer_shape_arity():
    with pytest.raises(mx.MXNetError, match="badop.*infer_shape"):
        OpDef("badop", lambda *a: ([], []),
              infer_shape=lambda attrs: None)


def test_register_validates_infer_type_arity():
    with pytest.raises(mx.MXNetError, match="badop2.*infer_type"):
        OpDef("badop2", lambda *a: ([], []),
              infer_type=lambda: None)


def test_register_rejects_required_kwonly():
    with pytest.raises(mx.MXNetError, match="keyword-only"):
        OpDef("badop3", lambda *a: ([], []),
              infer_shape=lambda attrs, shapes, *, mode: None)


def test_register_detects_out_known_capability():
    op2 = OpDef("okop2", lambda *a: ([], []),
                infer_shape=lambda attrs, shapes: (shapes, [shapes[0]], []))
    assert op2._infer_accepts_out is False
    op3 = OpDef("okop3", lambda *a: ([], []),
                infer_shape=lambda attrs, shapes, out_known=None:
                (shapes, [shapes[0]], []))
    assert op3._infer_accepts_out is True
    assert OpDef("okop4", lambda *a: ([], [])).shape_passthrough is False
    assert OpDef("okop5", lambda *a: ([], []),
                 shape_passthrough=True).shape_passthrough is True


def test_registered_ops_all_validate():
    """Every op already in the registry satisfies the registration-time
    signature contract (the check ran at import; re-assert explicitly)."""
    from mxnet_tpu.ops.registry import OP_REGISTRY, \
        _validate_infer_signature
    for name, op in OP_REGISTRY.items():
        _validate_infer_signature(name, "infer_shape", op.infer_shape)
        _validate_infer_signature(name, "infer_type", op.infer_type)
