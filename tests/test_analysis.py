"""Static-analysis (graph verifier & hazard linter) tests.

Seeded-hazard fixtures — use-after-donation, nondeterministic bucket
order, cache-churn attrs, and one per precision-flow rule
(QT701–QT705) — each tripping exactly one rule, plus zero-false-
positive gates over the bundled model zoo (f32 / simulated-bf16 /
int8-quantized) and the ZeRO/scan/bucketed configurations, the GV/HS
rule set, bind-time warn/raise surfaces, telemetry mirroring,
suppression, the registration-time infer-signature validation, the
Pallas kernel-spec validator (PK9xx), the env-var doc-sync audit, and
the cost-metadata consistency contract.
"""
import json
import logging

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.analysis import (AnalysisContext, RULES, lint_json,
                                lint_executor, lint_module, lint_symbol,
                                run_passes)
from mxnet_tpu.kvstore_sched import BucketScheduler
from mxnet_tpu.ops.registry import OpDef
from mxnet_tpu.program_cache import attr_cache_stable


def _precision_rules(sym, **ctx_kwargs):
    report = run_passes(AnalysisContext(symbol=sym, **ctx_kwargs),
                        passes=["precision_flow"])
    return report


def _two_fc():
    """Two same-shape FC layers: aliasing one weight cell onto the
    other keeps every shape consistent (the donation fixture must trip
    DA201 alone, not a shape rule)."""
    d = mx.sym.var("data")
    h = mx.sym.FullyConnected(d, num_hidden=16, name="fc1")
    h = mx.sym.Activation(h, act_type="relu", name="r1")
    h = mx.sym.FullyConnected(h, num_hidden=16, name="fc2")
    return mx.sym.SoftmaxOutput(h, name="softmax")


def _fused_module():
    mod = mx.mod.Module(_two_fc(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (8, 16))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params()
    mod.init_optimizer(kvstore=None)
    assert mod._fused_armed
    return mod


def _mlp():
    d = mx.sym.var("data")
    h = mx.sym.FullyConnected(d, num_hidden=8, name="fc1")
    h = mx.sym.Activation(h, act_type="relu", name="r1")
    h = mx.sym.FullyConnected(h, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(h, name="softmax")


# ------------------------------------------------------ seeded fixtures
def test_fixture_use_after_donation():
    """Aliasing a second arg name onto a donated param cell trips DA201
    and nothing else."""
    mod = _fused_module()
    exe = mod._exec_group.executor
    i1 = exe.arg_names.index("fc1_weight")
    i2 = exe.arg_names.index("fc2_weight")
    exe.arg_arrays[i2] = exe.arg_arrays[i1]
    report = lint_module(mod)
    assert report.rules == {"DA201"}
    assert len(report) == 1
    d = report.errors[0]
    assert "fc1_weight" in d.message and "fc2_weight" in d.message


def test_fixture_nondeterministic_bucket_order():
    """Equal-priority keys staged from two push calls in one window
    trip CO301 (multiworker audit) and nothing else."""
    sched = BucketScheduler(lambda x: x, lambda k, c, v: None,
                            lambda: 1 << 30)
    sched.note_push_call()
    sched.stage(3, None, np.zeros(4, np.float32), priority=0)
    sched.note_push_call()
    sched.stage(5, None, np.zeros(4, np.float32), priority=0)
    report = run_passes(AnalysisContext(sched=sched,
                                        assume_multiworker=True))
    assert report.rules == {"CO301"}
    assert len(report) == 1
    # same plan is fine on a single worker (no divergence possible)
    assert not len(run_passes(AnalysisContext(sched=sched)))


def test_fixture_cache_churn_attr():
    """An array-valued op attr trips RC401 and nothing else."""
    net = _mlp()
    node = net._outputs[0][0]
    node.attrs["debug_buffer"] = np.arange(3)
    report = lint_symbol(net, shapes={"data": (2, 8)})
    assert report.rules == {"RC401"}
    assert len(report) == 1
    assert "debug_buffer" in report.warnings[0].message


# -------------------------------------------------- zero-false-positive
MODEL_SHAPES = [
    ("mlp", lambda m: m.mlp.get_symbol(10), {"data": (8, 784)}),
    ("lenet", lambda m: m.lenet.get_symbol(10), {"data": (8, 1, 28, 28)}),
    ("alexnet", lambda m: m.alexnet.get_symbol(10),
     {"data": (2, 3, 224, 224)}),
    ("vgg16", lambda m: m.vgg.get_symbol(10, 16),
     {"data": (1, 3, 224, 224)}),
    ("resnet20", lambda m: m.resnet.get_symbol(10, 20, "3,32,32"),
     {"data": (4, 3, 32, 32)}),
    ("inception_bn", lambda m: m.inception_bn.get_symbol(10),
     {"data": (1, 3, 224, 224)}),
    ("inception_v3", lambda m: m.inception_v3.get_symbol(10),
     {"data": (1, 3, 299, 299)}),
]


@pytest.mark.parametrize("name,build,shapes", MODEL_SHAPES,
                         ids=[m[0] for m in MODEL_SHAPES])
def test_bundled_models_lint_clean(name, build, shapes):
    from mxnet_tpu import models
    report = lint_symbol(build(models), shapes=shapes)
    assert not len(report), f"{name}: {report.format()}"


@pytest.mark.parametrize("name,build,shapes", MODEL_SHAPES,
                         ids=[m[0] for m in MODEL_SHAPES])
def test_bundled_models_bf16_precision_clean(name, build, shapes):
    """Simulated-bf16 compute over the zoo: the QT7xx pass must stay
    quiet (the mixed-precision entry cast is uniform — no mixing)."""
    from mxnet_tpu import models
    report = lint_symbol(build(models), shapes=shapes,
                         compute_dtype="bfloat16")
    assert not len(report), f"{name}@bf16: {report.format()}"


@pytest.mark.parametrize("name,build,shapes", MODEL_SHAPES,
                         ids=[m[0] for m in MODEL_SHAPES])
def test_bundled_models_int8_quantized_lint_clean(name, build, shapes):
    """The int8 quant-rewritten zoo lints clean: declared int8 cells,
    Quantized* weight contracts, no QT/GV findings."""
    from mxnet_tpu import models
    qsym, _qargs = _quantized_model(lambda: build(models), shapes)
    report = lint_symbol(qsym, shapes=shapes)
    assert not len(report), f"{name}@int8: {report.format()}"


def test_gv105_quantized_cells_bind_without_warning():
    """GV105 regression gate: the quant rewrite's declared __dtype__
    int8 cells must bind int8 and pass dtype validation with zero
    warn-mode findings — for the MLP and a convnet."""
    from mxnet_tpu import models
    cases = [(models.mlp.get_symbol(10), {"data": (8, 784)}),
             (models.lenet.get_symbol(10), {"data": (8, 1, 28, 28)})]
    for sym, shapes in cases:
        qsym, qargs = _quantized_model(lambda s=sym: s, shapes)
        exe = qsym.simple_bind(ctx=mx.cpu(), grad_req="null",
                               validate=None, **shapes)
        # the executor honored the declarations (int8 cells bound)
        bound = dict(zip(exe.arg_names, exe.arg_arrays))
        qcells = [nm for nm in bound if nm.endswith("_q")]
        assert qcells
        for nm in qcells:
            assert str(np.dtype(bound[nm].dtype)) == "int8", nm
        report = lint_executor(exe)
        assert not len(report), report.format()


def test_fused_module_lint_clean():
    """The plain fused (replicated) arrangement has zero findings."""
    report = lint_module(_fused_module())
    assert not len(report), report.format()


def test_zero_scan_config_lint_clean():
    """The ZeRO-1 + K-step-scan arrangement on the 8-device mesh —
    the config test_zero/test_scan_fit exercise — has zero findings."""
    X = np.random.rand(32, 8).astype(np.float32)
    Y = np.zeros(32, np.float32)
    it = mx.io.NDArrayIter(X, Y, batch_size=8, label_name="softmax_label")
    mod = mx.mod.Module(_mlp(), context=[mx.cpu(i) for i in range(8)])
    mod.fit(it, num_epoch=1, zero_stage=1, steps_per_dispatch=2,
            kvstore=None)
    assert mod._exec_group._zero_plan is not None
    report = lint_module(mod)
    assert not len(report), report.format()


def test_kvstore_bucket_plan_lint_clean():
    """Module.update's push contract — ONE call, distinct priorities —
    audits clean even under the multiworker assumption."""
    kv = mx.kv.create("dist_sync")
    try:
        kv.init(0, mx.nd.zeros((4,)))
        kv.init(1, mx.nd.zeros((4,)))
        kv.push([1, 0], [mx.nd.ones((4,)), mx.nd.ones((4,))],
                priority=[1, 0])
        kv.pull([0, 1], [mx.nd.zeros((4,)), mx.nd.zeros((4,))])
        report = run_passes(AnalysisContext(kvstore=kv, sched=kv._sched,
                                            assume_multiworker=True))
        assert not len(report), report.format()
    finally:
        kv.close()


# ----------------------------------------------------- precision flow
def test_fixture_qt701_silent_f32_upcast():
    """A stock-f32 creation op mixed into a bf16 compute graph widens
    the chain silently -> QT701 and nothing else."""
    net = mx.sym.var("a") + mx.sym.zeros((4, 8))
    report = _precision_rules(net, compute_dtype="bfloat16")
    assert report.rules == {"QT701"}
    assert len(report) == 1
    # same graph at full f32: no reduced inputs, no finding
    assert not len(_precision_rules(net))


def test_fixture_qt702_unrewritten_quant_weight():
    """A Quantized op fed a float weight (no int8+scale rewrite) is an
    error -> QT702 alone."""
    q = mx.sym.QuantizedFullyConnected(
        mx.sym.var("data"), mx.sym.var("w"),
        mx.sym.var("s", dtype="float32"), num_hidden=8, no_bias=True,
        name="qfc")
    report = _precision_rules(q)
    assert report.rules == {"QT702"}
    assert report.errors and "w" in report.errors[0].message


def test_fixture_qt703_shared_int8_weight():
    """The int8 weight also feeding a float consumer -> QT703 alone."""
    wq = mx.sym.var("w_q", dtype="int8")
    q = mx.sym.QuantizedFullyConnected(
        mx.sym.var("data"), wq, mx.sym.var("s", dtype="float32"),
        num_hidden=8, no_bias=True, name="qfc")
    report = _precision_rules(mx.Group([q, mx.sym.sum(wq)]))
    assert report.rules == {"QT703"}
    assert "w_q" in report.errors[0].message


def test_fixture_qt704_dequant_requant_roundtrip():
    """int8 -> float -> (movement) -> int8 is a round trip -> QT704."""
    v = mx.sym.var("q", dtype="int8")
    f = mx.sym.Flatten(mx.sym.Cast(v, dtype="float32"))
    report = _precision_rules(mx.sym.Cast(f, dtype="int8"))
    assert report.rules == {"QT704"}
    # a single explicit dequant (no requant) is NOT a round trip
    assert not len(_precision_rules(mx.sym.Cast(v, dtype="float32")))


def test_fixture_qt705_narrow_loss_accumulation():
    """A loss head whose declared input dtype is bf16 -> QT705 alone;
    compute_dtype-driven reduction (f32 master params) is exempt."""
    d = mx.sym.var("data", dtype="bfloat16")
    w = mx.sym.var("w", dtype="bfloat16")
    b = mx.sym.var("b", dtype="bfloat16")
    fc = mx.sym.FullyConnected(d, weight=w, bias=b, num_hidden=4,
                               name="fc")
    report = _precision_rules(mx.sym.SoftmaxOutput(fc, name="softmax"))
    assert report.rules == {"QT705"}
    # the exemption: an all-f32 graph under bf16 compute_dtype keeps
    # its f32 master accumulation -> clean
    clean = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=4,
                              name="fc2"), name="softmax2")
    assert not len(_precision_rules(clean, compute_dtype="bfloat16"))


def _quantized_model(build, shapes):
    """Int8 quant-rewrite of a bundled model with zero weights (the
    rewrite and lint surfaces are shape/dtype-driven)."""
    import jax.numpy as jnp
    from mxnet_tpu.ops.quant import quantize_symbol
    sym = build()
    arg_shapes, _o, _a = sym.infer_shape(**shapes)
    args = {nm: mx.nd.NDArray(jnp.zeros(s, np.float32))
            for nm, s in zip(sym.list_arguments(), arg_shapes)
            if nm not in shapes}
    return quantize_symbol(sym, args)


# -------------------------------------------------------- graph verifier
def test_gv_duplicate_variable():
    a = mx.sym.var("x")
    b = mx.sym.var("x")
    report = lint_symbol(a + b)
    assert report.rules == {"GV103"}


def test_gv_duplicate_node_name():
    d = mx.sym.var("data")
    h = mx.sym.FullyConnected(d, weight=mx.sym.var("w1"),
                              bias=mx.sym.var("b1"), num_hidden=4,
                              name="fc")
    h = mx.sym.FullyConnected(h, weight=mx.sym.var("w2"),
                              bias=mx.sym.var("b2"), num_hidden=4,
                              name="fc")
    report = lint_symbol(h, shapes={"data": (2, 4)})
    assert report.rules == {"GV104"}


def test_gv_inference_conflict_is_error():
    a = mx.sym.var("a")
    b = mx.sym.var("b")
    report = lint_symbol(a + b, shapes={"a": (2, 3), "b": (4, 5)})
    assert report.rules == {"GV101"}
    msg = report.errors[0].message
    assert "_plus" in msg and "(2, 3)" in msg and "(4, 5)" in msg


def test_gv_stall_without_infer_shape():
    """An op with neither infer_shape nor shape_passthrough stalls on a
    partial input shape -> GV107 names the op. (Flatten used to be the
    fixture; it now registers a pure-python infer_shape for the
    trace-free memory planner, so a scratch op seeds the stall.)"""
    from mxnet_tpu.ops.registry import OP_REGISTRY, register
    from mxnet_tpu.symbol import _create
    if "lint_stall_fixture" not in OP_REGISTRY:
        register("lint_stall_fixture",
                 simple=lambda attrs, x: x.reshape(x.shape[0], -1))
    d = mx.sym.var("data", shape=(0, 5))     # batch unknown
    net = _create("lint_stall_fixture", [d])
    report = lint_symbol(net)
    assert "GV107" in report.rules
    assert any(f.op == "lint_stall_fixture" for f in report)


def test_flatten_infers_without_abstract_eval():
    """Flatten's registered infer_shape propagates partial batch dims
    in pure python (no eval_shape fallback)."""
    d = mx.sym.var("data", shape=(0, 5))
    net = mx.sym.Flatten(d)
    assert "GV107" not in lint_symbol(net).rules
    _, outs, _ = net.infer_shape_partial()
    assert outs == [(0, 5)]


def test_gv_shape_passthrough_flag_infers_and_silences():
    """softmax declares shape_passthrough: partial shapes flow through
    it (forward and backward) and GV107 stays quiet."""
    d = mx.sym.var("data", shape=(0, 7))
    net = mx.sym.softmax(d)
    report = lint_symbol(net)
    assert "GV107" not in report.rules
    # and the flag actually propagates shapes both ways
    _, outs, _ = net.infer_shape_partial(data=(4, 7))
    assert outs == [(4, 7)]


def test_gv_dtype_conflict():
    """An explicitly bound array conflicting with the declared dtype
    trips GV105 (simple_bind now honors declarations itself — the
    conflict needs a user-provided array)."""
    d = mx.sym.var("data", dtype="float16")
    net = mx.sym.FullyConnected(d, num_hidden=4, name="fc")
    args = {"data": mx.nd.zeros((2, 8)),           # f32, declared f16
            "fc_weight": mx.nd.zeros((4, 8)),
            "fc_bias": mx.nd.zeros((4,))}
    exe = net.bind(mx.cpu(), args=args, grad_req="null", validate=None)
    from mxnet_tpu.analysis import lint_executor
    report = lint_executor(exe)
    assert "GV105" in report.rules


def test_simple_bind_honors_declared_dtype():
    """simple_bind binds a declared __dtype__ cell (the quant tier's
    int8 weights) instead of silently upcasting to f32."""
    d = mx.sym.var("data", dtype="float16")
    net = mx.sym.FullyConnected(d, num_hidden=4, name="fc")
    exe = net.simple_bind(ctx=mx.cpu(), data=(2, 8), validate=None)
    bound = dict(zip(exe.arg_names, exe.arg_arrays))
    assert str(np.dtype(bound["data"].dtype)) == "float16"
    from mxnet_tpu.analysis import lint_executor
    assert "GV105" not in lint_executor(exe).rules


def test_json_dead_node_and_dangling_input():
    doc = {"nodes": [
        {"op": "null", "name": "a", "inputs": []},
        {"op": "null", "name": "dead", "inputs": []},
        {"op": "_copy", "name": "c", "inputs": [[0, 0, 0]]}],
        "arg_nodes": [0, 1], "heads": [[2, 0, 0]]}
    report = lint_json(json.dumps(doc))
    assert "GV108" in report.rules
    assert any(f.node == "dead" for f in report)

    doc2 = {"nodes": [{"op": "_copy", "name": "c",
                       "inputs": [[5, 0, 0]]}],
            "arg_nodes": [], "heads": [[0, 0, 0]]}
    report2 = lint_json(json.dumps(doc2))
    assert "GV106" in report2.rules


def test_saved_symbol_roundtrip_lints_clean(tmp_path):
    net = _mlp()
    path = tmp_path / "mlp-symbol.json"
    net.save(str(path))
    report = lint_json(path.read_text(), shapes={"data": (8, 8)})
    assert not len(report), report.format()


# ------------------------------------------------- donation / collective
def test_da_donated_param_as_label_input():
    mod = _fused_module()
    g = mod._exec_group
    g.label_names = list(g.label_names) + ["fc1_weight"]
    report = lint_module(mod)
    assert report.rules == {"DA203"}


def test_da_shared_cells_with_fused_plan():
    mod = _fused_module()
    mod._exec_group._shared_param_names = {"fc1_weight"}
    report = lint_module(mod)
    assert report.rules == {"DA202"}


def test_da_bucket_buffer_alias():
    sched = BucketScheduler(lambda x: x, lambda k, c, v: None,
                            lambda: 1 << 30)
    buf = np.zeros(4, np.float32)
    sched.note_push_call()
    sched.stage(0, None, buf, priority=1)
    sched.stage(1, None, buf, priority=0)
    report = run_passes(AnalysisContext(sched=sched))
    assert report.rules == {"DA204"}


def test_co_watched_order_mismatch():
    mod = _fused_module()
    mod._exec_group._fused_watched = \
        list(reversed(mod._exec_group._fused_watched))
    report = lint_module(mod)
    assert report.rules == {"CO303"}


def test_co_zero_plan_with_dist_kvstore():
    mod = _fused_module()
    kv = mx.kv.create("dist_sync")
    try:
        from mxnet_tpu.parallel.zero import ZeroPlan
        mod._exec_group._zero_plan = ZeroPlan.__new__(ZeroPlan)
        mod._exec_group._zero_plan.axis = "data"
        mod._exec_group._zero_plan.n = 8
        mod._kvstore = kv
        report = lint_module(mod)
        assert "CO302" in report.rules
    finally:
        mod._kvstore = None
        kv.close()


# ------------------------------------------------------------- host sync
def test_hs_naive_engine(monkeypatch):
    monkeypatch.setenv("MXNET_ENGINE_TYPE", "NaiveEngine")
    net = _mlp()
    exe = net.simple_bind(ctx=mx.cpu(), data=(2, 8), validate=None)
    from mxnet_tpu.analysis import lint_executor
    report = lint_executor(exe)
    assert report.rules == {"HS501"}


def test_hs_monitor_tap_is_info():
    net = _mlp()
    exe = net.simple_bind(ctx=mx.cpu(), data=(2, 8), validate=None)
    exe.set_monitor_callback(lambda name, arr: None)
    from mxnet_tpu.analysis import lint_executor
    report = lint_executor(exe)
    assert report.rules == {"HS502"}
    assert report.infos and not report.errors and not report.warnings


# ------------------------------------------------------- retrace / cache
def test_rc_uncacheable_binding():
    net = _mlp()
    exe = net.simple_bind(ctx=mx.cpu(), data=(2, 8), validate=None)
    exe._prog_cache_base = None
    from mxnet_tpu.analysis import lint_executor
    report = lint_executor(exe)
    assert report.rules == {"RC402"}


def test_attr_cache_stable_predicate():
    assert attr_cache_stable(3)[0]
    assert attr_cache_stable("relu")[0]
    assert attr_cache_stable((1, 2, 3))[0]
    assert attr_cache_stable(1.5)[0]
    assert not attr_cache_stable(float("nan"))[0]
    assert not attr_cache_stable(np.arange(2))[0]
    assert not attr_cache_stable(lambda x: x)[0]
    assert not attr_cache_stable(object())[0]


# ------------------------------------------------------ surfaces / modes
def test_bind_validate_raise_mode():
    a = mx.sym.var("a")
    b = mx.sym.var("b")
    bad = a + b
    with pytest.raises(mx.MXNetError, match="GV101"):
        bad.bind(mx.cpu(), args={"a": mx.nd.ones((2, 3)),
                                 "b": mx.nd.ones((4, 5))},
                 validate="raise")


def test_bind_validate_warn_mode_logs(caplog):
    a = mx.sym.var("a")
    b = mx.sym.var("b")
    bad = a + b
    with caplog.at_level(logging.WARNING, logger="mxnet_tpu.analysis"):
        exe = bad.bind(mx.cpu(), args={"a": mx.nd.ones((2, 3)),
                                       "b": mx.nd.ones((4, 5))},
                       validate="warn")
    assert exe is not None          # warn mode never blocks the bind
    assert any("GV101" in rec.message for rec in caplog.records)


def test_env_validate_mode(monkeypatch):
    monkeypatch.setenv("MXNET_GRAPH_VALIDATE", "raise")
    a = mx.sym.var("a")
    b = mx.sym.var("b")
    with pytest.raises(mx.MXNetError, match="GV101"):
        (a + b).bind(mx.cpu(), args={"a": mx.nd.ones((2, 3)),
                                     "b": mx.nd.ones((4, 5))})
    # per-call override beats the env
    exe = (a + b).bind(mx.cpu(), args={"a": mx.nd.ones((2, 3)),
                                       "b": mx.nd.ones((4, 5))},
                       validate="warn")
    assert exe is not None


def test_lint_disable_suppression(monkeypatch):
    net = _mlp()
    node = net._outputs[0][0]
    node.attrs["debug_buffer"] = np.arange(3)
    monkeypatch.setenv("MXNET_LINT_DISABLE", "RC401")
    assert not len(lint_symbol(net, shapes={"data": (2, 8)}))
    monkeypatch.setenv("MXNET_LINT_DISABLE", "retrace_churn")
    assert not len(lint_symbol(net, shapes={"data": (2, 8)}))
    monkeypatch.setenv("MXNET_LINT_DISABLE", "all")
    assert not len(lint_symbol(net, shapes={"data": (2, 8)}))
    monkeypatch.delenv("MXNET_LINT_DISABLE")
    assert len(lint_symbol(net, shapes={"data": (2, 8)})) == 1


def test_findings_mirror_into_telemetry():
    from mxnet_tpu.telemetry import flightrec, metrics
    mod = _fused_module()
    exe = mod._exec_group.executor
    i1 = exe.arg_names.index("fc1_weight")
    i2 = exe.arg_names.index("fc2_weight")
    exe.arg_arrays[i2] = exe.arg_arrays[i1]
    before = metrics.get_metric("analysis.lint.findings", rule="DA201",
                                severity="error")
    base = before.value if before else 0
    flightrec.clear()
    lint_module(mod)
    after = metrics.get_metric("analysis.lint.findings", rule="DA201",
                               severity="error")
    assert after is not None and after.value == base + 1
    recs = [r for r in flightrec.get_records()
            if r.get("kind") == "lint.finding"]
    assert recs and recs[-1]["rule"] == "DA201"


def test_diagnose_renders_lint_findings(tmp_path):
    """tools/diagnose.py shows lint findings in a crash report."""
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    try:
        import diagnose
    finally:
        sys.path.pop(0)
    report = {
        "type": "crash_report", "time": "t", "pid": 1, "where": "bind",
        "ring": [{"kind": "lint.finding", "ts_us": 1, "rule": "DA201",
                  "severity": "error", "node": "fc1_weight",
                  "message": "one buffer is bound twice"}],
        "metrics": {"counters":
                    {'analysis.lint.findings{rule="DA201",'
                     'severity="error"}': 1}},
    }
    path = tmp_path / "crash.json"
    path.write_text(json.dumps(report))
    text = diagnose.render_file(str(path))
    assert "lint findings" in text and "DA201" in text


def test_rule_catalog_consistency():
    """Every rule id used in this file exists; severities are valid."""
    for rule, (sev, title) in RULES.items():
        assert sev in ("info", "warning", "error")
        assert title


# ------------------------------------------------------------ mxlint CLI
def _mxlint_main():
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    try:
        import mxlint
    finally:
        sys.path.pop(0)
    return mxlint.main


def test_mxlint_check_gate(capsys):
    """The CI gate: every bundled model + the two example graphs lint
    clean (exit 0). Runs mxlint in-process so tier-1 pays no second
    interpreter/jax start-up."""
    main = _mxlint_main()
    assert main(["--check"]) == 0
    out = capsys.readouterr().out
    assert "models/resnet20" in out and "examples/dcgan.generator" in out
    assert "0 error(s)" in out


def test_perfwatch_check_gate(capsys):
    """The perf-trajectory CI gate, next to ``mxlint --check``: the
    watchdog passes on the repo's real bench history and the recorded
    benchmark gates (exit 0), in-process. A perf-shaped regression —
    a doctored payload or a failing recorded gate — fails CI the same
    way a lint rule does (tests/test_trace.py seeds both)."""
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    try:
        import perfwatch
    finally:
        sys.path.pop(0)
    assert perfwatch.main(["--check"]) == 0
    out = capsys.readouterr().out
    assert "perfwatch OK" in out and "0 regression(s)" in out


def test_mxlint_json_file_exit_codes(tmp_path, capsys):
    main = _mxlint_main()
    good = _mlp()
    good_path = tmp_path / "good-symbol.json"
    good.save(str(good_path))
    assert main([str(good_path), "--shape", "data=8,8"]) == 0

    bad = {"nodes": [{"op": "_copy", "name": "c",
                      "inputs": [[5, 0, 0]]}],
           "arg_nodes": [], "heads": [[0, 0, 0]]}
    bad_path = tmp_path / "bad-symbol.json"
    bad_path.write_text(json.dumps(bad))
    assert main([str(bad_path)]) == 1          # nonzero on errors
    out = capsys.readouterr().out
    assert "GV106" in out

    # warnings pass by default, fail under --strict
    warn = {"nodes": [
        {"op": "null", "name": "a", "inputs": []},
        {"op": "null", "name": "dead", "inputs": []},
        {"op": "_copy", "name": "c", "inputs": [[0, 0, 0]]}],
        "arg_nodes": [0, 1], "heads": [[2, 0, 0]]}
    warn_path = tmp_path / "warn-symbol.json"
    warn_path.write_text(json.dumps(warn))
    assert main([str(warn_path)]) == 0
    assert main([str(warn_path), "--strict"]) == 1
    assert main([]) == 2                        # nothing to lint


def test_mxlint_rules_listing(capsys):
    main = _mxlint_main()
    assert main(["--rules"]) == 0
    out = capsys.readouterr().out
    for rule in RULES:
        assert rule in out


def test_mxlint_env_audit_gate(capsys):
    """The doc-sync CI gate: zero drift, exit 0."""
    main = _mxlint_main()
    assert main(["--env-audit"]) == 0
    out = capsys.readouterr().out
    assert "0 undocumented, 0 dead rows" in out


def test_mxlint_metric_audit_gate(capsys):
    """The metric-catalog CI gate: zero drift both ways, exit 0."""
    main = _mxlint_main()
    assert main(["--metric-audit"]) == 0
    out = capsys.readouterr().out
    assert "0 undocumented, 0 dead rows" in out


def test_mxlint_memory_plan_cli(capsys):
    """--memory-plan renders a per-policy plan; a tiny capacity trips
    ME801 (exit 1), headroom trips ME802 (info, exit 0)."""
    main = _mxlint_main()
    assert main(["--memory-plan", "resnet20", "--policy", "none",
                 "--policy", "dots", "--batch", "64"]) == 0
    out = capsys.readouterr().out
    assert "memory plan for resnet20" in out and "residuals" in out

    assert main(["--memory-plan", "resnet20", "--batch", "256",
                 "--capacity-gb", "0.05"]) == 1
    out = capsys.readouterr().out
    assert "ME801" in out

    assert main(["--memory-plan", "resnet20", "--batch", "64",
                 "--policy", "all", "--capacity-gb", "4"]) == 0
    out = capsys.readouterr().out
    assert "ME802" in out

    assert main(["--memory-plan", "nosuchmodel"]) == 2


def test_mxlint_precision_audit_cli(capsys):
    """The quant/mixed-precision zoo audits clean through the CLI
    (mlp only here — the full corpus runs under --check in CI)."""
    main = _mxlint_main()
    assert main(["--precision-audit", "--compute-dtype",
                 "float32"]) == 0
    out = capsys.readouterr().out
    assert "models/mlp@float32" in out and "models/mlp@int8" in out


def test_mxlint_mfu_audit_includes_planner_bytes(capsys):
    main = _mxlint_main()
    assert main(["--mfu-audit"]) == 0
    out = capsys.readouterr().out
    assert "planner per-op" in out and "BatchNorm" in out


# ------------------------------------ Pallas kernel validator (PK9xx)
def _dummy_variant(attrs, inputs, aux, is_train, rng):
    return list(inputs), []


def test_fixture_pk901_vmem_overflow():
    """A declared working set past the per-generation VMEM budget
    fails loudly at registration with PK901."""
    op = OpDef("pk901_fixture", lambda *a: ([], []))
    with pytest.raises(mx.MXNetError, match="PK901"):
        op.add_variant("pallas", _dummy_variant, kernel_spec={
            "tiles": [((256, 32768), "float32")] * 2,   # 64 MiB
            "dtypes": ("float32",)})
    assert "pallas" not in op.variants


def test_fixture_pk902_misaligned_tile():
    """Lane (last % 128) and sublane (dtype rows) misalignment both
    fail with PK902."""
    op = OpDef("pk902_fixture", lambda *a: ([], []))
    with pytest.raises(mx.MXNetError, match="PK902"):
        op.add_variant("pallas", _dummy_variant, kernel_spec={
            "tiles": [((8, 100), "float32")], "dtypes": ("float32",)})
    with pytest.raises(mx.MXNetError, match="PK902"):
        op.add_variant("pallas", _dummy_variant, kernel_spec={
            "tiles": [((8, 128), "int8")],     # int8 packs 32 rows
            "dtypes": ("int8",)})


def test_fixture_pk903_dtype_coverage():
    """Empty or gate-uncoverable dtype sets fail with PK903."""
    op = OpDef("pk903_fixture", lambda *a: ([], []))
    with pytest.raises(mx.MXNetError, match="PK903"):
        op.add_variant("pallas", _dummy_variant, kernel_spec={
            "tiles": [((8, 128), "float32")], "dtypes": ()})
    with pytest.raises(mx.MXNetError, match="PK903"):
        op.add_variant("pallas", _dummy_variant, kernel_spec={
            "tiles": [((8, 128), "float32")],
            "dtypes": ("float64",)})


def test_registered_pallas_variants_all_declare_specs():
    """Every shipped production Pallas variant carries a validated
    kernel_spec — an infeasible production kernel can no longer
    register. (User rtc kernels may omit the spec.)"""
    from mxnet_tpu.analysis.kernelcheck import validate_kernel_spec
    from mxnet_tpu.ops.registry import get_op
    shipped = ["SoftmaxOutput", "FusedConvBNReLU", "LayerNorm",
               "FusedBiasGeLU", "Embedding", "sgd_mom_update",
               "adam_update", "QuantizedFullyConnected",
               "QuantizedConvolution", "pallas_sgd_mom_update",
               "pallas_flash_attention", "attention"]
    for name in shipped:
        rec = get_op(name).variants["pallas"]
        spec = rec.get("kernel_spec")
        assert spec is not None, f"{name}:pallas has no kernel_spec"
        validate_kernel_spec(name, "pallas", spec)    # idempotent


def test_valid_kernel_spec_registers():
    op = OpDef("pk_ok_fixture", lambda *a: ([], []))
    op.add_variant("pallas", _dummy_variant, kernel_spec={
        "tiles": [((256, 128), "float32"), ((32, 128), "int8")],
        "dtypes": ("float32", "int8")})
    assert op.variants["pallas"]["kernel_spec"]["dtypes"] == (
        "float32", "int8")


# ------------------------------------------- env-var doc-sync audit
def test_env_audit_in_sync():
    """MXNET_* env reads and docs/env_var.md rows match (the CI gate
    behind ``mxlint --env-audit``)."""
    import os
    from mxnet_tpu.analysis import envaudit
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    result = envaudit.audit(repo)
    assert not result["undocumented"], result["undocumented"]
    assert not result["dead"], result["dead"]
    # sanity: the scan actually sees the surface, both spellings
    assert "MXNET_GRAPH_VALIDATE" in result["code_vars"]
    assert any(p.startswith("MXNET_RETRY_")
               for p in result["code_prefixes"])


def test_env_audit_detects_drift(tmp_path):
    """A synthetic tree with an undocumented read and a dead row."""
    from mxnet_tpu.analysis import envaudit
    pkg = tmp_path / "mxnet_tpu"
    pkg.mkdir()
    (pkg / "m.py").write_text(
        "import os\nX = os.environ.get('MXNET_SECRET_KNOB', '')\n")
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "env_var.md").write_text("* `MXNET_GHOST_KNOB` — unused\n")
    result = envaudit.audit(str(tmp_path))
    assert result["undocumented"] == ["MXNET_SECRET_KNOB"]
    assert result["dead"] == ["MXNET_GHOST_KNOB"]


# --------------------------------------- metric-name doc-sync audit
def test_metric_audit_in_sync():
    """Recorded metric names and the docs/telemetry.md Metric catalog
    match both ways (the CI gate behind ``mxlint --metric-audit``)."""
    import os
    from mxnet_tpu.analysis import metricaudit
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    result = metricaudit.audit(repo)
    assert not result["undocumented"], result["undocumented"]
    assert not result["dead"], result["dead"]
    # sanity: the scan really sees the surface — exact names, the
    # hist= keyword feed, and f-string/metric_prefix families
    assert "module.fit.batches" in result["code_names"]
    assert "executor.compile.seconds" in result["code_names"]
    assert any(p.startswith("serve.decode.")
               for p in result["code_prefixes"])
    assert "step.phase." in result["doc_prefixes"]


def test_metric_audit_detects_drift(tmp_path):
    """A synthetic tree with an unrecorded catalog row and an
    uncatalogued recording, in every resolution mode the scanner
    claims: literal, concatenation, hist= keyword, f-string family."""
    from mxnet_tpu.analysis import metricaudit
    pkg = tmp_path / "mxnet_tpu"
    pkg.mkdir()
    (pkg / "m.py").write_text(
        "from telemetry import counter, gauge, histogram, span\n"
        "def f(key):\n"
        "    counter('secret.items').inc()\n"
        "    name = 'secret.step'\n"
        "    histogram(name + '.seconds').observe(1)\n"
        "    gauge(f'family.{key}').set(1)\n"
        "    span('x', hist='hooked.seconds')\n")
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "telemetry.md").write_text(
        "# Telemetry\n\n"
        "prose mentioning `unrelated.metric` outside the catalog\n\n"
        "## Metric catalog\n\n"
        "| `secret.items` | counter | things |\n"
        "| `ghost.metric` | gauge | recorded by nothing |\n\n"
        "## Next section\n")
    result = metricaudit.audit(str(tmp_path))
    assert result["undocumented"] == ["hooked.seconds", "secret.step.seconds",
                                      "family.*"]
    assert result["dead"] == ["ghost.metric"]
    assert result["ok"] is False

    # adding the missing rows (a `<placeholder>` row covers the
    # f-string family) and dropping the dead one restores sync
    (docs / "telemetry.md").write_text(
        "## Metric catalog\n\n"
        "| `secret.items` | counter | things |\n"
        "| `secret.step.seconds` | histogram | step wall |\n"
        "| `hooked.seconds` | histogram | span feed |\n"
        "| `family.<key>` | gauge | per-key family |\n")
    assert metricaudit.audit(str(tmp_path))["ok"] is True


# --------------------------------------- cost-metadata consistency
def test_every_flops_estimator_has_bytes():
    """The planner and the roofline both fold per-op byte counts: an
    op with flops but no bytes (or vice versa) under-counts one axis
    while looking covered. The registry must have none."""
    from mxnet_tpu.ops.cost import partial_cost_ops
    assert partial_cost_ops() == []


def test_planner_per_op_bytes_cover_cost_ops():
    """The planner's per-op byte table names the ops that dominate the
    resnet20 residual bill, and they all carry cost metadata."""
    from mxnet_tpu import models
    from mxnet_tpu.analysis import memplan
    from mxnet_tpu.ops.registry import get_op
    plan = memplan.plan_symbol(
        models.resnet.get_symbol(10, 20, "3,32,32"),
        {"data": (4, 3, 32, 32)}, policy="none")
    assert plan["per_op_bytes"]
    assert "BatchNorm" in plan["per_op_bytes"]
    for op in plan["per_op_bytes"]:
        assert get_op(op).has_cost(), op


# -------------------------------- registration-time infer validation (S2)
def test_register_validates_infer_shape_arity():
    with pytest.raises(mx.MXNetError, match="badop.*infer_shape"):
        OpDef("badop", lambda *a: ([], []),
              infer_shape=lambda attrs: None)


def test_register_validates_infer_type_arity():
    with pytest.raises(mx.MXNetError, match="badop2.*infer_type"):
        OpDef("badop2", lambda *a: ([], []),
              infer_type=lambda: None)


def test_register_rejects_required_kwonly():
    with pytest.raises(mx.MXNetError, match="keyword-only"):
        OpDef("badop3", lambda *a: ([], []),
              infer_shape=lambda attrs, shapes, *, mode: None)


def test_register_detects_out_known_capability():
    op2 = OpDef("okop2", lambda *a: ([], []),
                infer_shape=lambda attrs, shapes: (shapes, [shapes[0]], []))
    assert op2._infer_accepts_out is False
    op3 = OpDef("okop3", lambda *a: ([], []),
                infer_shape=lambda attrs, shapes, out_known=None:
                (shapes, [shapes[0]], []))
    assert op3._infer_accepts_out is True
    assert OpDef("okop4", lambda *a: ([], [])).shape_passthrough is False
    assert OpDef("okop5", lambda *a: ([], []),
                 shape_passthrough=True).shape_passthrough is True


def test_registered_ops_all_validate():
    """Every op already in the registry satisfies the registration-time
    signature contract (the check ran at import; re-assert explicitly)."""
    from mxnet_tpu.ops.registry import OP_REGISTRY, \
        _validate_infer_signature
    for name, op in OP_REGISTRY.items():
        _validate_infer_signature(name, "infer_shape", op.infer_shape)
        _validate_infer_signature(name, "infer_type", op.infer_type)
