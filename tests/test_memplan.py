"""Static memory planner (analysis/memplan.py, ME8xx) tests.

The load-bearing gate: the planner's residual estimate for resnet20
b32 agrees with the traced ``remat.residual_bytes`` figure within 5%
for ALL THREE remat policies — with the planner performing zero
compiles and zero traces (pinned via the program-cache compile counter
and a jax trace hook). Plus: the exec-group static fast path
cross-checks ``fused_memory_report``, the batch-headroom gate consumes
the plan, ME801/802 fire on seeded fixtures through the lint pass, the
SPMD/ZeRO/int8 layout awareness, and the diagnose rendering.
"""
import json

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import remat
from mxnet_tpu.analysis import AnalysisContext, memplan, run_passes
from mxnet_tpu.models import resnet

BATCH = 32
SHAPES = {"data": (BATCH, 3, 32, 32), "softmax_label": (BATCH,)}


def _resnet20():
    return resnet.get_symbol(10, 20, "3,32,32")


def _armed_module(policy):
    remat.set_active(policy)
    mod = mx.mod.Module(_resnet20(), context=mx.cpu())
    mod.bind(data_shapes=[("data", SHAPES["data"])],
             label_shapes=[("softmax_label", SHAPES["softmax_label"])])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(kvstore=None, optimizer="sgd",
                       optimizer_params={"learning_rate": 0.05,
                                         "momentum": 0.9})
    assert mod._fused_armed
    return mod


@pytest.fixture(autouse=True)
def _reset_remat():
    yield
    remat.set_active(None)


# ------------------------------------------------- the agreement gate
@pytest.mark.parametrize("policy", remat.POLICIES)
def test_planner_agrees_with_traced_residuals(policy):
    """Planner residual estimate vs the eval_shape-traced
    ``remat.residual_bytes`` on resnet20 b32: within 5% per policy,
    and the summed fused-step total (params + state + batch +
    residuals) within 5% too."""
    mod = _armed_module(policy)
    report = mod._exec_group.fused_memory_report()
    assert report is not None and report["policy"] == policy

    plan = memplan.plan_symbol(_resnet20(), SHAPES, policy=policy)
    measured = report["residual_bytes"]
    assert abs(plan["residual_bytes"] - measured) <= 0.05 * measured, (
        policy, plan["residual_bytes"], measured)

    keys = ("residual_bytes", "param_bytes", "state_bytes",
            "batch_bytes")
    total_plan = sum(plan[k] for k in keys)
    total_meas = sum(report[k] for k in keys)
    assert abs(total_plan - total_meas) <= 0.05 * total_meas


@pytest.mark.parametrize("policy", remat.POLICIES)
def test_planner_agrees_on_lenet(policy):
    """Second agreement point with a different op mix (max pooling,
    tanh, dense tail — the rules resnet20 alone does not exercise)."""
    from mxnet_tpu.models import lenet
    shapes = {"data": (40, 1, 28, 28), "softmax_label": (40,)}
    remat.set_active(policy)
    mod = mx.mod.Module(lenet.get_symbol(2), context=mx.cpu())
    mod.bind(data_shapes=[("data", shapes["data"])],
             label_shapes=[("softmax_label", shapes["softmax_label"])])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(kvstore=None, optimizer="sgd",
                       optimizer_params={"learning_rate": 0.05,
                                         "momentum": 0.9})
    report = mod._exec_group.fused_memory_report()
    plan = memplan.plan_symbol(lenet.get_symbol(2), shapes,
                               policy=policy)
    measured = report["residual_bytes"]
    assert abs(plan["residual_bytes"] - measured) <= 0.05 * measured, (
        policy, plan["residual_bytes"], measured)


def test_planner_is_trace_free():
    """Zero compiles AND zero jax traces while planning: the plan is
    pure python over the symbol graph."""
    import jax
    before = mx.program_cache.compile_count()
    calls = []
    orig = jax.eval_shape

    def spy(*a, **k):
        calls.append(a)
        return orig(*a, **k)

    jax.eval_shape = spy
    try:
        for policy in remat.POLICIES:
            memplan.plan_symbol(_resnet20(), SHAPES, policy=policy)
    finally:
        jax.eval_shape = orig
    assert mx.program_cache.compile_count() == before
    assert not calls


def test_policy_ordering_and_components():
    """all < dots < none residuals; components are sane."""
    plans = {p: memplan.plan_symbol(_resnet20(), SHAPES, policy=p)
             for p in remat.POLICIES}
    assert plans["all"]["residual_bytes"] < \
        plans["dots"]["residual_bytes"] < \
        plans["none"]["residual_bytes"]
    p = plans["none"]
    assert p["param_bytes"] > 0 and p["batch_bytes"] > 0
    assert p["state_bytes"] == p["grad_bytes"]      # sgd_mom: 1x f32
    assert p["peak_bytes_per_device"] >= p["residual_bytes"]
    assert p["batch_size"] == BATCH


# ------------------------------------------- exec-group static fast path
def test_static_memory_plan_cross_checks_eval_shape():
    """The static fast path reproduces fused_memory_report's component
    bytes (exact for params/state/batch, <=5% residuals) and feeds the
    batch-headroom gate the same way (the eval_shape cross-check the
    tentpole promises)."""
    from mxnet_tpu.telemetry.memory import batch_headroom
    mod = _armed_module("dots")
    g = mod._exec_group
    report = g.fused_memory_report()
    plan = g.static_memory_plan()
    assert plan["param_bytes"] == report["param_bytes"]
    assert plan["state_bytes"] == report["state_bytes"]
    assert plan["batch_bytes"] == report["batch_bytes"]
    resid = report["residual_bytes"]
    assert abs(plan["residual_bytes"] - resid) <= 0.05 * resid

    # identical headroom decisions from the two per-sample figures
    # (1% slack over the 128 rung so the <=5% residual delta cannot
    # straddle the exact boundary)
    buckets = (32, 64, 128, 256)
    fixed = report["param_bytes"] + report["state_bytes"]
    per_sample_meas = (resid + report["batch_bytes"]) / BATCH
    budget = fixed + per_sample_meas * 128 * 1.06
    static = batch_headroom(budget, fixed, plan["per_sample_bytes"],
                            buckets)
    traced = batch_headroom(budget, fixed, per_sample_meas, buckets)
    assert static == traced == 128

    plan2 = g.static_memory_plan(buckets=buckets,
                                 capacity_bytes=int(budget))
    assert plan2["headroom_bucket"] in (64, 128)


def test_static_memory_plan_without_armed_optimizer():
    """The fast path works on a bare binding (no fused step, no
    optimizer): state falls back to the multiplier estimate."""
    mod = mx.mod.Module(_resnet20(), context=mx.cpu())
    mod.bind(data_shapes=[("data", SHAPES["data"])],
             label_shapes=[("softmax_label",
                            SHAPES["softmax_label"])])
    plan = mod._exec_group.static_memory_plan(policy="none",
                                              )
    assert plan["residual_bytes"] > 0
    assert plan["param_bytes"] > 0


# -------------------------------------------------- layout awareness
def test_int8_params_count_one_byte():
    """Quantized weights cost 1 B/element in the plan."""
    import jax.numpy as jnp
    from mxnet_tpu.ops.quant import quantize_symbol
    from mxnet_tpu.models import mlp as mlp_mod
    sym = mlp_mod.get_symbol(10)
    shapes = {"data": (8, 784)}
    arg_shapes, _o, _a = sym.infer_shape(**shapes)
    args = {nm: mx.nd.NDArray(jnp.zeros(s, np.float32))
            for nm, s in zip(sym.list_arguments(), arg_shapes)
            if nm not in shapes}
    qsym, _ = quantize_symbol(sym, args)
    fplan = memplan.plan_symbol(sym, shapes, for_training=False)
    qplan = memplan.plan_symbol(qsym, shapes, for_training=False)
    # int8 weights + f32 scales land well under half the float bytes
    assert qplan["param_bytes"] < 0.5 * fplan["param_bytes"]
    assert qplan["grad_bytes"] == 0 and qplan["residual_bytes"] == 0


def test_zero_shards_state_and_data_divides():
    """ZeRO divides optimizer state 1/N; activations divide over the
    data axis."""
    one = memplan.plan_symbol(_resnet20(), SHAPES, policy="none")
    sharded = memplan.plan_symbol(_resnet20(), SHAPES, policy="none",
                                  n_data=8, zero=True)
    assert sharded["state_bytes_per_device"] == one["state_bytes"] // 8
    assert sharded["peak_bytes_per_device"] < one["peak_bytes_per_device"]


def test_spmd_plan_shards_params():
    """An SpmdPlan param spec shrinks per-device param bytes."""
    class FakePlan:
        def param_shard_fraction(self, name, shape):
            return 0.25 if name.endswith("_weight") else 1.0

    base = memplan.plan_symbol(_resnet20(), SHAPES, policy="all")
    spmd = memplan.plan_symbol(_resnet20(), SHAPES, policy="all",
                               spmd_plan=FakePlan())
    assert spmd["param_bytes"] < base["param_bytes"]


def test_spmd_param_shard_fraction():
    from jax.sharding import PartitionSpec as P
    from mxnet_tpu.parallel.mesh import MeshConfig, build_mesh
    from mxnet_tpu.parallel.spmd import SpmdPlan
    import jax
    mesh = build_mesh(MeshConfig(data=4, model=2),
                      devices=jax.devices()[:8])
    plan = SpmdPlan(mesh)
    plan.param_specs["w"] = P("model", None)
    assert plan.param_shard_fraction("w", (64, 32)) == 0.5
    assert plan.param_shard_fraction("other", (64, 32)) == 1.0
    # non-divisible dims stay whole (XLA would pad/replicate)
    assert plan.param_shard_fraction("w", (63, 32)) == 1.0


@pytest.mark.parametrize("policy", remat.POLICIES)
def test_armed_module_lints_clean_per_policy(policy):
    """Zero-false-positive gate along the remat axis: a fused resnet20
    module armed under each policy runs the FULL pass set clean."""
    from mxnet_tpu.analysis import lint_module
    mod = _armed_module(policy)
    report = lint_module(mod)
    assert not len(report), f"{policy}: {report.format()}"


# ------------------------------------------------ ME8xx lint findings
def test_fixture_me801_predicted_oom():
    """A capacity below the predicted peak trips ME801 (error) through
    the memory_planner pass, and nothing else."""
    report = run_passes(AnalysisContext(
        symbol=_resnet20(), known_shapes=SHAPES,
        memplan={"capacity_bytes": 10 << 20, "policy": "none"}),
        passes=["memory_planner"])
    assert report.rules == {"ME801"}
    assert report.errors


def test_fixture_me802_headroom_admits_bucket():
    """Ample capacity + a bucket ladder trips the ME802 info finding."""
    report = run_passes(AnalysisContext(
        symbol=_resnet20(), known_shapes=SHAPES,
        memplan={"capacity_bytes": 8 << 30, "policy": "dots",
                 "buckets": (32, 64, 128, 256)}),
        passes=["memory_planner"])
    assert report.rules == {"ME802"}
    assert report.infos


def test_memory_planner_pass_inert_by_default(monkeypatch):
    """No memplan options, no env budget -> the pass is a no-op (the
    warm-bind overhead gate depends on this)."""
    monkeypatch.delenv("MXNET_LINT_MEMPLAN_BUDGET", raising=False)
    report = run_passes(AnalysisContext(symbol=_resnet20(),
                                        known_shapes=SHAPES),
                        passes=["memory_planner"])
    assert not len(report)


def test_memory_planner_env_budget(monkeypatch):
    """MXNET_LINT_MEMPLAN_BUDGET arms the pass at bind-time lint."""
    monkeypatch.setenv("MXNET_LINT_MEMPLAN_BUDGET", "50M")
    report = run_passes(AnalysisContext(symbol=_resnet20(),
                                        known_shapes=SHAPES),
                        passes=["memory_planner"])
    assert "ME801" in report.rules


# --------------------------------------------------------- rendering
def test_plan_telemetry_and_diagnose_section(tmp_path):
    """record_plan lands memplan.* gauges + a flight note, and
    tools/diagnose.py renders the 'memory plan' section."""
    import os
    import sys
    from mxnet_tpu.telemetry import flightrec, metrics
    plan = memplan.plan_symbol(_resnet20(), SHAPES, policy="dots")
    flightrec.clear()
    memplan.record_plan(plan, model="resnet20")
    g = metrics.get_metric("memplan.peak_bytes_per_device",
                           model="resnet20", policy="dots")
    assert g is not None and g.value == plan["peak_bytes_per_device"]

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    try:
        import diagnose
    finally:
        sys.path.pop(0)
    crash = {
        "type": "crash_report", "time": "t", "pid": 1, "where": "bind",
        "ring": [{"kind": "memplan.plan", "ts_us": 1,
                  "model": "resnet20", "policy": "dots", "batch": 32,
                  "peak_bytes": plan["peak_bytes_per_device"],
                  "residual_bytes": plan["residual_bytes"]}],
        "metrics": {"gauges": {
            'memplan.peak_bytes_per_device{model="resnet20",'
            'policy="dots"}': plan["peak_bytes_per_device"]}},
    }
    path = tmp_path / "crash.json"
    path.write_text(json.dumps(crash))
    text = diagnose.render_file(str(path))
    assert "memory plan" in text and "resnet20" in text


def test_format_plan_renders():
    plan = memplan.plan_symbol(_resnet20(), SHAPES, policy="all")
    text = memplan.format_plan(plan, model="resnet20",
                               capacity_bytes=1 << 30)
    assert "policy=all" in text and "peak/device" in text \
        and "capacity" in text
