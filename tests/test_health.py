"""Training-health plane (ISSUE 17): in-program run statistics,
divergence detection, and automated triage.

Tier-1 coverage for the three layers:

* detector units — ``telemetry.health`` is jax-free, so every rule
  (loss_spike / loss_plateau / grad explosion+collapse / update-ratio
  band / nonfinite), the MAD warm-up, cooldown and policy resolution
  run on scripted stat dicts;
* the in-program stats — an armed K=8 scan fit is bit-identical to an
  unarmed one (the stats are read-only ys), arming keys the program
  cache (``("health", armed)`` — the regression that motivated it), and
  both fit paths deliver every step's observation despite the
  readiness-gated drain lag;
* triage — the ``warn → snapshot → checkpoint → raise`` ladder lands
  flight-recorder reports and emergency ``CheckpointManager`` commits,
  the ``train.health.triage`` fault point injects, and the seeded
  lr-bomb run diverges end-to-end: detect → emergency commit →
  ``AnomalyError`` → ``/healthz`` 503 → exact resume with zero
  steady-state compiles — plus the 2-rank fleetstat attribution that
  names the rank whose detector fired first.
"""
import json
import os
import sys
import urllib.error
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import program_cache
from mxnet_tpu.telemetry import (fleet, flightrec, health, metrics,
                                 opsd)
from mxnet_tpu.telemetry.sentinel import AnomalyError

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(ROOT, "tools")

BATCH = 4
N_BATCHES = 16
CLASSES = 3
FEATS = 6

# detector knobs that keep every rule quiet on a toy run (warm-up loss
# drops fast and lr=0.05 gives window update-ratios a real optimizer
# run would alarm on)
QUIET = {"k_mad": 1e12, "plateau_tol": 0.0, "ratio_band": (0.0, 1e30),
         "collapse_frac": 0.0}

_HEALTH_ENV = ("MXNET_TRAIN_HEALTH", "MXNET_TRAIN_HEALTH_POLICY",
               "MXNET_TRAIN_HEALTH_WINDOW", "MXNET_TRAIN_HEALTH_K",
               "MXNET_CKPT_DIR", "MXNET_FAULTS")


def _tool(name):
    sys.path.insert(0, TOOLS)
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


@pytest.fixture(autouse=True)
def _clean_health(monkeypatch):
    """Every test starts unarmed with a fresh monitor/registry and
    leaves no forced arming, live endpoint, or resized ring behind."""
    for var in _HEALTH_ENV:
        monkeypatch.delenv(var, raising=False)
    health.configure(armed=None)
    mx.telemetry.reset()
    yield
    opsd.stop_ops()
    health.configure(armed=None)
    mx.telemetry.reset()
    mx.telemetry.disable()
    flightrec.configure(capacity=512, dump_dir=".")


# ------------------------------------------------------------ fit helper
def _mlp():
    data = mx.sym.var("data")
    fc1 = mx.sym.FullyConnected(data=data, num_hidden=8, name="fc1")
    # every layer named: auto-name counters are process-global, and a
    # drifting symbol hash would defeat the cross-module program-cache
    # hits the zero-compile resume assertion measures
    act = mx.sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = mx.sym.FullyConnected(act, num_hidden=CLASSES, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def _data():
    rs = np.random.RandomState(0)
    X = rs.rand(N_BATCHES * BATCH, FEATS).astype(np.float32)
    y = rs.randint(0, CLASSES, (N_BATCHES * BATCH,)).astype(np.float32)
    return X, y


def _init_args():
    rs = np.random.RandomState(1)
    return {
        "fc1_weight": mx.nd.array(rs.randn(8, FEATS).astype(np.float32)
                                  * 0.1),
        "fc1_bias": mx.nd.array(np.zeros(8, np.float32)),
        "fc2_weight": mx.nd.array(rs.randn(CLASSES, 8).astype(np.float32)
                                  * 0.1),
        "fc2_bias": mx.nd.array(np.zeros(CLASSES, np.float32)),
    }


def _fit(K=1, health_arg=None, checkpoint=None, resume=None,
         num_epoch=1, sched=None, cursors=None):
    """One deterministic training run; returns the module."""
    X, y = _data()
    mx.random.seed(7)
    it = mx.io.NDArrayIter(X, y, batch_size=BATCH)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    opt_params = {"learning_rate": 0.05}
    if sched is not None:
        opt_params["lr_scheduler"] = sched
    cb = None
    if cursors is not None:
        cb = lambda p: cursors.append((p.epoch, p.nbatch))
    mod.fit(it, num_epoch=num_epoch, steps_per_dispatch=K,
            arg_params={k: v.copy() for k, v in _init_args().items()},
            optimizer="sgd", optimizer_params=opt_params,
            batch_end_callback=cb, checkpoint=checkpoint, resume=resume,
            health=health_arg)
    return mod


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=5) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _stats(loss=1.0, gn=1.0, pn=5.0, ur=1e-3, nonfinite=0.0):
    return {"loss": [loss], "grad_norm": gn, "param_norm": pn,
            "update_ratio": ur, "nonfinite": nonfinite}


# -------------------------------------------------------- detector units
def test_loss_spike_fires_over_mad_threshold():
    mon = health.HealthMonitor(window=8, k_mad=6.0, policy="warn",
                               **{k: v for k, v in QUIET.items()
                                  if k != "k_mad"})
    # alternating jitter keeps the plateau counter quiet at tol=0
    for i in range(10):
        assert mon.observe(_stats(loss=1.0 + 0.001 * (-1) ** i)) == []
    fired = mon.observe(_stats(loss=9.0))
    assert [f["rule"] for f in fired] == ["loss_spike"]
    f = fired[0]
    assert f["policy"] == "warn"
    assert f["value"] == pytest.approx(9.0)
    assert f["threshold"] < 9.0
    assert mon.state == 2 and health.STATE_NAMES[mon.state] == "diverged"
    # the firing landed on the metric surface
    assert metrics.counter("train.health.firings",
                           rule="loss_spike").value == 1
    assert metrics.gauge("train.health.rule_fired",
                         rule="loss_spike").value == 11
    assert metrics.gauge("train.health.first_firing",
                         rule="loss_spike").value == 11
    assert metrics.gauge("train.health.state").value == 2
    assert metrics.gauge("train.health.loss", head="0").value \
        == pytest.approx(9.0)
    # ...and the flight ring, carrying the full stat window
    recs = [r for r in flightrec.get_records()
            if r["kind"] == "train.health"]
    assert len(recs) == 1 and recs[0]["rule"] == "loss_spike"
    assert len(recs[0]["window"]["loss"]) == 8


def test_mad_detectors_hold_during_warmup():
    mon = health.HealthMonitor(window=8, k_mad=6.0, policy="warn",
                               **{k: v for k, v in QUIET.items()
                                  if k != "k_mad"})
    # 7 samples < the 8-sample warm-up: even a wild value stays quiet
    for i in range(7):
        mon.observe(_stats(loss=1.0 + 0.001 * (-1) ** i, gn=1.0))
    assert mon.observe(_stats(loss=500.0, gn=500.0)) == []


def test_grad_explosion_and_collapse():
    quiet = {k: v for k, v in QUIET.items()
             if k not in ("k_mad", "collapse_frac")}
    mon = health.HealthMonitor(window=8, k_mad=6.0, collapse_frac=0.01,
                               policy="warn", **quiet)
    jig = lambda i: 1.0 + 0.001 * (-1) ** i   # keeps plateau_tol=0 quiet
    for i in range(10):
        assert mon.observe(_stats(gn=jig(i), loss=jig(i))) == []
    fired = mon.observe(_stats(gn=80.0))
    assert [f["rule"] for f in fired] == ["grad_explosion"]
    assert mon.state == 2

    mon2 = health.HealthMonitor(window=8, k_mad=6.0, collapse_frac=0.01,
                                policy="warn", **quiet)
    for i in range(10):
        mon2.observe(_stats(gn=jig(i), loss=jig(i)))
    fired = mon2.observe(_stats(gn=1e-6))
    assert [f["rule"] for f in fired] == ["grad_collapse"]
    assert mon2.state == 1      # collapse degrades, never diverges


def test_update_ratio_band():
    quiet = {k: v for k, v in QUIET.items() if k != "ratio_band"}
    mon = health.HealthMonitor(window=8, ratio_band=(1e-4, 0.5),
                               policy="warn", **quiet)
    fired = mon.observe(_stats(ur=0.8))     # band rules need no warm-up
    assert [f["rule"] for f in fired] == ["update_ratio_high"]

    mon2 = health.HealthMonitor(window=8, ratio_band=(1e-4, 0.5),
                                policy="warn", **quiet)
    fired = mon2.observe(_stats(ur=1e-6, gn=1.0))
    assert [f["rule"] for f in fired] == ["update_ratio_low"]
    # a zero-grad step legitimately moves nothing: no firing
    mon3 = health.HealthMonitor(window=8, ratio_band=(1e-4, 0.5),
                                policy="warn", **quiet)
    assert mon3.observe(_stats(ur=0.0, gn=0.0)) == []


def test_loss_plateau_fires_after_full_flat_window():
    mon = health.HealthMonitor(window=8, plateau_tol=1e-3, policy="warn",
                               **{k: v for k, v in QUIET.items()
                                  if k != "plateau_tol"})
    firings = []
    for _ in range(9):
        firings.append(mon.observe(_stats(loss=1.0)))
    # obs 1 seeds the EMA; obs 2..8 are 7 flat steps; obs 9 is the 8th
    assert all(f == [] for f in firings[:-1])
    assert [f["rule"] for f in firings[-1]] == ["loss_plateau"]
    assert mon.state == 1


def test_nonfinite_rule_from_flag_and_from_values():
    mon = health.HealthMonitor(window=8, policy="warn", **QUIET)
    fired = mon.observe(_stats(nonfinite=1.0))
    assert [f["rule"] for f in fired] == ["nonfinite"]
    mon2 = health.HealthMonitor(window=8, policy="warn", **QUIET)
    fired = mon2.observe(_stats(loss=float("nan")))
    assert [f["rule"] for f in fired] == ["nonfinite"]
    assert mon2.state == 2


def test_cooldown_bounds_refires():
    mon = health.HealthMonitor(window=8, ratio_band=(0.0, 0.5),
                               cooldown=4, policy="warn",
                               **{k: v for k, v in QUIET.items()
                                  if k != "ratio_band"})
    fired_at = [n for n in range(1, 11)
                if mon.observe(_stats(ur=0.9,
                                      loss=1.0 + 0.001 * (-1) ** n))]
    assert fired_at == [1, 6]       # held down for `cooldown` obs
    assert metrics.gauge("train.health.first_firing",
                         rule="update_ratio_high").value == 1
    assert metrics.gauge("train.health.rule_fired",
                         rule="update_ratio_high").value == 6


def test_flight_ring_health_records_stay_bounded():
    """Bugfix satellite: a pathological rule storm cannot grow the ring
    past its capacity."""
    flightrec.configure(capacity=8)
    mon = health.HealthMonitor(window=8, ratio_band=(0.0, 0.5),
                               cooldown=0, policy="warn",
                               **{k: v for k, v in QUIET.items()
                                  if k != "ratio_band"})
    for i in range(50):
        assert mon.observe(_stats(ur=0.9, loss=1.0 + 0.01 * (-1) ** i))
    recs = flightrec.get_records()
    assert len(recs) <= 8
    assert any(r["kind"] == "train.health" for r in recs)


# ------------------------------------------------------ policies / state
def test_policy_resolution_precedence(monkeypatch):
    # built-in default, then the monitor's own spec
    assert health.resolve_policy("loss_spike") == "warn"
    mon = health.HealthMonitor(policy={"loss_spike": "snapshot"})
    assert mon.policy_for("loss_spike") == "snapshot"
    assert mon.policy_for("grad_collapse") == "warn"
    # env spec: bare default + per-rule overrides (sentinel rides too)
    monkeypatch.setenv("MXNET_TRAIN_HEALTH_POLICY",
                       "checkpoint,nonfinite=raise,sentinel=raise")
    assert health.resolve_policy("loss_spike") == "checkpoint"
    assert health.resolve_policy("nonfinite") == "raise"
    assert health.resolve_policy("sentinel") == "raise"
    assert mon.policy_for("grad_collapse") == "checkpoint"
    # an explicit override beats everything
    assert health.resolve_policy("nonfinite", override="warn") == "warn"
    # malformed policy tokens are ignored, not fatal
    monkeypatch.setenv("MXNET_TRAIN_HEALTH_POLICY", "bogus")
    assert health.resolve_policy("loss_spike") == "warn"


def test_armed_override_and_reset():
    assert not health.armed()
    health.configure(armed=True)
    assert health.armed()
    health.configure(armed=False)
    assert not health.armed()
    # reset() keeps the override (fit pins arming process-wide)...
    health.configure(armed=True)
    health.reset()
    assert health.armed()
    # ...and configure(armed=None) restores the env default
    health.configure(armed=None)
    assert not health.armed()


def test_status_document_shape():
    doc = health.status()
    assert doc == {"armed": False, "state": 0, "state_name": "ok",
                   "observations": 0, "rules": [], "series": {}}
    health.observe(_stats(), epoch=0, nbatch=0)
    doc = health.status()
    assert doc["observations"] == 1 and doc["state_name"] == "ok"
    assert doc["series"]["grad_norm"] == [1.0]


# ---------------------------------------------------------------- triage
def test_escalate_snapshot_writes_flight_report(tmp_path):
    flightrec.configure(dump_dir=str(tmp_path))
    health.escalate("loss_plateau", "snapshot", "loss went flat")
    files = [f for f in os.listdir(tmp_path)
             if f.startswith("mxnet_crash_")]
    assert len(files) == 1
    text = (tmp_path / files[0]).read_text()
    assert "train.health.loss_plateau" in text
    assert "loss went flat" in text


def test_escalate_checkpoint_lands_emergency_commit(tmp_path):
    mod = _fit(K=1, health_arg=False)
    d = str(tmp_path / "ck")
    mod._ckpt_manager = mx.checkpoint.CheckpointManager(d)
    try:
        health.bind_triage(mod)     # the fit-loop binding escalate uses
        health.escalate("grad_explosion", "checkpoint",
                        "grad norm blew up", epoch=0, nbatch=5)
        mod._ckpt_manager.wait()
    finally:
        health.release_triage()
        mod._ckpt_manager.close()
    assert mx.checkpoint.latest_checkpoint(d) is not None
    assert metrics.counter("train.health.emergency_ckpts").value == 1
    recs = [r for r in flightrec.get_records()
            if r["kind"] == "train.health.ckpt"]
    assert recs and recs[-1]["rule"] == "grad_explosion"
    assert recs[-1]["nbatch"] == 5


def test_escalate_checkpoint_without_manager_warns(caplog):
    with caplog.at_level("WARNING"):
        health.escalate("loss_spike", "checkpoint", "spiked")
    assert "no checkpoint manager" in caplog.text


def test_escalate_raise_commits_then_raises(tmp_path):
    mod = _fit(K=1, health_arg=False)
    d = str(tmp_path / "ck")
    mod._ckpt_manager = mx.checkpoint.CheckpointManager(d)
    try:
        with pytest.raises(AnomalyError, match="nonfinite"):
            health.escalate("nonfinite", "raise", "NaN in the stats",
                            module=mod, epoch=0, nbatch=9)
    finally:
        mod._ckpt_manager.close()
    # the raise path blocks on the commit, so the run is resumable
    assert mx.checkpoint.latest_checkpoint(d) is not None


def test_triage_fault_injection_point():
    from mxnet_tpu import faults
    with faults.scope("train.health.triage:once,error=value"):
        with pytest.raises(ValueError):
            health.escalate("loss_spike", "warn", "spiked")
        assert faults.fired("train.health.triage") == 1
    health.escalate("loss_spike", "warn", "spiked")   # unarmed: clean


# ----------------------------------------------------- fit integration
def test_armed_scan_fit_bit_identical_and_keys_program_cache():
    """The acceptance gate: the stats are read-only outputs — an armed
    K=8 scan run ends bit-for-bit where the unarmed one does — and
    arming keys the program cache so the two never share a trace."""
    mu = _fit(K=8, health_arg=False, num_epoch=2)
    ma = _fit(K=8, health_arg=dict(QUIET, policy="warn"), num_epoch=2)
    au, _ = mu.get_params()
    aa, _ = ma.get_params()
    assert sorted(au) == sorted(aa)
    for k in sorted(au):
        np.testing.assert_array_equal(au[k].asnumpy(), aa[k].asnumpy(),
                                      err_msg=k)
    # every step produced an observation, drained by the epoch-end flush
    assert health.monitor().observations == 2 * N_BATCHES
    assert health.status()["rules"] == []
    assert health.state() == 0
    # cache-key regression: ("health", armed) is a key element
    ku = mu._exec_group._fused_cache_key
    ka = ma._exec_group._fused_cache_key
    assert ("health", False) in ku
    assert ("health", True) in ka
    assert ku != ka


def test_plain_path_observes_and_dict_knobs_reach_monitor():
    _fit(K=1, health_arg=dict(QUIET, policy="warn", k_mad=9.0))
    mon = health.monitor()
    assert mon.observations == N_BATCHES
    assert mon.k_mad == 9.0         # fit(health={...}) knobs applied
    doc = health.status()
    assert doc["armed"] and doc["state_name"] == "ok"
    assert len(doc["series"]["grad_norm"]) == min(N_BATCHES, mon.window)
    assert all(g > 0.0 for g in doc["series"]["grad_norm"])
    assert all(0.0 < r < 1.0 for r in doc["series"]["update_ratio"])


class _LRBomb(mx.lr_scheduler.LRScheduler):
    """Benign lr until one poisoned update: a seeded, reproducible
    divergence (finite but violent, so the emergency commit stays
    loadable)."""

    def __init__(self, at, boost):
        super().__init__()
        self.at = at
        self.boost = boost

    def _rate(self, num_update):
        return self.boost if num_update == self.at else self.base_lr


def test_seeded_divergence_end_to_end(tmp_path):
    """The seeded-divergence satellite: an lr bomb mid-epoch must be
    detected in-program, land an emergency commit, raise AnomalyError
    out of fit, flip /healthz to 503 — and the run must resume from the
    commit with zero steady-state compiles."""
    flightrec.configure(dump_dir=str(tmp_path / "dumps"))
    d = str(tmp_path / "ck")
    with pytest.raises(AnomalyError):
        # spike detectors live (k_mad=6); the rules a healthy toy run
        # trips anyway (ratio band, plateau, collapse) stay quiet
        _fit(K=8, num_epoch=2, checkpoint=d, sched=_LRBomb(12, 1e3),
             health_arg=dict(QUIET, policy="raise", k_mad=6.0))
    fired = {f["rule"] for f in health.status()["rules"]}
    assert fired & {"loss_spike", "grad_explosion", "nonfinite"}
    assert health.state() == 2
    assert metrics.counter("train.health.emergency_ckpts").value >= 1
    assert mx.checkpoint.latest_checkpoint(d) is not None

    # the live endpoint degrades: /healthz 503, /trainz shows the rules
    srv = mx.telemetry.serve_ops(port=0)
    code, body = _get(srv.url + "/healthz")
    doc = json.loads(body)
    assert code == 503 and doc["ok"] is False
    assert doc["train_health"]["state"] == 2
    assert doc["train_health"]["name"] == "diverged"
    assert doc["train_health"]["rules"] == sorted(fired)
    code, body = _get(srv.url + "/trainz")
    tdoc = json.loads(body)
    assert code == 200 and tdoc["state_name"] == "diverged"
    assert tdoc["rules"]
    opsd.stop_ops()

    # resume (benign schedule, detectors back to warn): completes,
    # fast-forwards past the commit cursor, re-uses the armed program
    c0 = program_cache.compile_count()
    cursors = []
    mod2 = _fit(K=8, num_epoch=2, checkpoint=d, resume=True,
                health_arg=dict(QUIET, policy="warn"), cursors=cursors)
    assert program_cache.compile_count() == c0
    assert cursors and cursors[0] != (0, 0)
    args, _ = mod2.get_params()
    for k, v in args.items():
        assert np.isfinite(v.asnumpy()).all(), k


# -------------------------------------------------- fleet attribution
def _rank_dump(path, rank, state, rules):
    """One synthesized per-rank jsonl dump carrying health gauges."""
    lines = [{"type": "meta", "schema": fleet.SCHEMA_VERSION,
              "rank": rank, "host": f"h{rank}", "pid": 100 + rank,
              "num_workers": 2, "generation": 0, "time_unix": 1000.0},
             {"type": "step", "wall_us": 10000,
              "phases_us": {"dispatch": 10000}},
             {"type": "gauge", "name": "train.health.state",
              "labels": {}, "value": state}]
    for rule, n in rules.items():
        lines.append({"type": "gauge",
                      "name": "train.health.first_firing",
                      "labels": {"rule": rule}, "value": n})
    path.write_text("\n".join(json.dumps(l) for l in lines) + "\n")
    return str(path)


def test_fleetstat_names_first_diverged_rank(tmp_path):
    """2-rank attribution: the fleet minimum of first-firing indices
    names the sick rank even after the blast radius trips its peer."""
    fleetstat = _tool("fleetstat")
    f0 = _rank_dump(tmp_path / "r0.jsonl", 0, 1,
                    {"grad_explosion": 120})
    f1 = _rank_dump(tmp_path / "r1.jsonl", 1, 2,
                    {"loss_spike": 40, "nonfinite": 55})
    doc = fleetstat.build([fleetstat.load_file(p) for p in (f0, f1)])
    th = doc["train_health"]
    assert th["by_rank"]["0"] == {"state": 1, "name": "degraded",
                                  "rules": {"grad_explosion": 120}}
    assert th["by_rank"]["1"]["name"] == "diverged"
    assert th["first"] == {"rank": "1", "rule": "loss_spike",
                           "observation": 40}
    text = fleetstat.render(doc)
    assert "FIRST DIVERGED: rank 1 — loss_spike at observation 40" \
        in text
    # byte-determinism under permuted input order
    doc2 = fleetstat.build([fleetstat.load_file(p) for p in (f1, f0)])
    assert fleetstat.render(doc2) == text
