"""Async checkpointing + exact resume (mxnet_tpu/checkpoint, ISSUE 9).

The fast (tier-1) half of the recovery story: single-process
kill/resume must be EXACT — params, optimizer state, update counts
(Adam bias correction / lr schedules), the rng chain feeding dropout,
and the epoch/batch cursor all bit-for-bit against an uninterrupted
run — plus the manager's atomic-commit/retention contracts, the
layout-independent optimizer-state transport (satellite: fused/ZeRO
paths round-trip through ``save_checkpoint``), the kvstore close/
dead-node seam, and the recovery env remapping. The multi-process
chaos gate lives in tests/test_chaos.py (@slow).
"""
import json
import os
import pickle

import numpy as np
import pytest

import jax

import mxnet_tpu as mx

BATCH = 4
N_BATCHES = 10
CLASSES = 3
FEATS = 6


def _mlp(dropout=0.3):
    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data=data, num_hidden=8, name="fc1")
    act = mx.sym.Activation(fc, act_type="relu")
    if dropout:
        act = mx.sym.Dropout(act, p=dropout)
    fc2 = mx.sym.FullyConnected(act, num_hidden=CLASSES, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def _data():
    rs = np.random.RandomState(0)
    X = rs.rand(N_BATCHES * BATCH, FEATS).astype(np.float32)
    y = rs.randint(0, CLASSES, (N_BATCHES * BATCH,)).astype(np.float32)
    return X, y


def _init_args():
    rs = np.random.RandomState(1)
    return {
        "fc1_weight": mx.nd.array(rs.randn(8, FEATS).astype(np.float32)
                                  * 0.1),
        "fc1_bias": mx.nd.array(np.zeros(8, np.float32)),
        "fc2_weight": mx.nd.array(rs.randn(CLASSES, 8).astype(np.float32)
                                  * 0.1),
        "fc2_bias": mx.nd.array(np.zeros(CLASSES, np.float32)),
    }


class _Kill(Exception):
    """Simulated SIGKILL at a batch boundary (the module object is
    abandoned exactly as a dead process abandons its memory)."""


def _run(kill_at=None, ckpt=None, resume=None, num_epoch=2, K=1,
         optimizer="adam", dropout=0.3, every=2, zero_stage=None,
         n_dev=1, seed=7):
    """One training run; returns (params, accs[(epoch, nbatch, acc)],
    module). ``kill_at`` raises out of fit at that (epoch, nbatch)'s
    batch_end_callback — before the boundary's checkpoint tick, like a
    real mid-run kill."""
    X, y = _data()
    mx.random.seed(seed)
    it = mx.io.NDArrayIter(X, y, batch_size=BATCH)
    ctx = mx.cpu() if n_dev == 1 else [mx.cpu(i) for i in range(n_dev)]
    mod = mx.mod.Module(_mlp(dropout), context=ctx)
    sched = mx.lr_scheduler.FactorScheduler(step=5, factor=0.5)
    accs = []

    def cb(p):
        accs.append((p.epoch, p.nbatch, p.eval_metric.get()[1]))
        if kill_at is not None and (p.epoch, p.nbatch) == kill_at:
            raise _Kill()

    mgr = mx.checkpoint.CheckpointManager(ckpt, every_n_batches=every) \
        if isinstance(ckpt, str) else ckpt
    opt_params = {"learning_rate": 0.05, "lr_scheduler": sched} \
        if optimizer == "adam" else \
        {"learning_rate": 0.05, "momentum": 0.9, "lr_scheduler": sched}
    try:
        mod.fit(it, num_epoch=num_epoch, steps_per_dispatch=K,
                batch_end_callback=cb, zero_stage=zero_stage,
                arg_params={k: v.copy() for k, v in _init_args().items()},
                optimizer=optimizer, optimizer_params=opt_params,
                checkpoint=mgr, resume=resume)
    except _Kill:
        pass
    finally:
        if mgr is not None:
            mgr.close()
    args, _ = mod.get_params()
    return {k: v.asnumpy() for k, v in args.items()}, accs, mod


def _fused_states_np(mod):
    return {k: [np.asarray(l) for l in jax.tree.leaves(v)]
            for k, v in mod._exec_group.export_fused_states().items()}


def _assert_states_equal(sa, sb):
    assert sa.keys() == sb.keys()
    for k in sa:
        for x, z in zip(sa[k], sb[k]):
            np.testing.assert_array_equal(x, z, err_msg=k)


# ------------------------------------------------------------ exact resume
def test_kill_resume_bit_for_bit(tmp_path):
    """The tier-1 acceptance gate: kill mid-epoch-1, resume in a fresh
    module, and end bit-for-bit where the uninterrupted run ends —
    params, Adam state, update counts (bias correction + FactorScheduler
    continuity), with dropout active (rng chain restore)."""
    d = str(tmp_path / "ck")
    pa, aa, ma = _run()
    pb, ab, mb = _run(kill_at=(1, 3), ckpt=d)
    # the killed run stopped early
    assert ab[-1][:2] == (1, 3)
    pc, ac, mc = _run(ckpt=d, resume=True, seed=999)  # seed overridden
    for k in pa:
        np.testing.assert_array_equal(pa[k], pc[k], err_msg=k)
    _assert_states_equal(_fused_states_np(ma), _fused_states_np(mc))
    assert mc._optimizer.num_update == ma._optimizer.num_update
    # resumed run fast-forwarded: its first trained batch is the cursor,
    # not batch 0 of epoch 0
    assert ac[0][0] >= 1


def test_resume_skips_exactly_to_cursor(tmp_path):
    """The resumed run's first callback lands on the checkpoint cursor
    (already-trained batches are consumed silently)."""
    d = str(tmp_path / "ck")
    _run(kill_at=(0, 5), ckpt=d, every=2)
    # ticks at batches 0..4 -> commits at cursors 2 and 4
    latest = mx.checkpoint.latest_checkpoint(d)
    assert latest is not None
    with open(os.path.join(latest[1], "manifest.json")) as f:
        cursor = json.load(f)["cursor"]
    assert (cursor["epoch"], cursor["nbatch"]) == (0, 4)
    _, ac, _ = _run(ckpt=d, resume=True)
    assert ac[0][:2] == (0, 4)


def test_scan_kill_resume_identical_loss_curve(tmp_path):
    """Satellite: K=4 scan run killed at batch N resumes with a loss
    curve identical to the unkilled run's, and bit-identical final
    params. The kill lands inside epoch 1's first window, so the
    resume cursor is the epoch boundary and every resumed batch's
    metric value is comparable 1:1 (the metric accumulator itself is
    epoch-scoped, not checkpointed — docs/checkpoint.md)."""
    d = str(tmp_path / "ck")
    pa, aa, _ = _run(K=4, every=1)
    _run(K=4, kill_at=(1, 1), ckpt=d, every=1)
    pc, ac, _ = _run(K=4, ckpt=d, resume=True, seed=999)
    for k in pa:
        np.testing.assert_array_equal(pa[k], pc[k], err_msg=k)
    # every batch the resumed run trained reports the same metric value
    # as the same batch of the uninterrupted run
    by_idx = {(e, n): v for e, n, v in aa}
    assert ac and ac[0][:2] == (1, 0)
    for e, n, v in ac:
        assert v == by_idx[(e, n)], (e, n)


def test_scan_resume_from_mid_epoch_window_boundary(tmp_path):
    """A kill past a mid-epoch window tick resumes AT that window
    boundary (cursor (1, 4)), replays the remaining windows, and still
    ends bit-identical."""
    d = str(tmp_path / "ck")
    pa, _, _ = _run(K=4, every=1)
    _run(K=4, kill_at=(1, 7), ckpt=d, every=1)
    latest = mx.checkpoint.latest_checkpoint(d)
    with open(os.path.join(latest[1], "manifest.json")) as f:
        cursor = json.load(f)["cursor"]
    assert (cursor["epoch"], cursor["nbatch"]) == (1, 4)
    pc, ac, _ = _run(K=4, ckpt=d, resume=True, seed=999)
    assert ac[0][:2] == (1, 4)
    for k in pa:
        np.testing.assert_array_equal(pa[k], pc[k], err_msg=k)


def test_resume_mid_window_cursor_under_larger_K(tmp_path):
    """A cursor cut under K=1 need not be window-aligned for a K=4
    resume: the first partial window fast-forwards through split
    singles. Numerics match the uninterrupted K=1 run to fp tolerance
    (scan ≡ singles, the test_scan_fit contract)."""
    d = str(tmp_path / "ck")
    pa, _, _ = _run(K=1, optimizer="sgd")
    _run(K=1, optimizer="sgd", kill_at=(0, 5), ckpt=d, every=3)
    latest = mx.checkpoint.latest_checkpoint(d)
    with open(os.path.join(latest[1], "manifest.json")) as f:
        assert json.load(f)["cursor"]["nbatch"] == 3   # not a K=4 edge
    pc, _, _ = _run(K=4, optimizer="sgd", ckpt=d, resume=True)
    for k in pa:
        np.testing.assert_allclose(pa[k], pc[k], rtol=2e-5, atol=2e-6,
                                   err_msg=k)


def test_restore_into_staged_arrangement(tmp_path):
    """A fused-run checkpoint restores into a module running the staged
    (monitor-installed) path: canonical by-name states project onto the
    per-index updater."""
    d = str(tmp_path / "ck")
    _run(kill_at=(1, 3), ckpt=d, optimizer="sgd")
    X, y = _data()
    mx.random.seed(7)
    it = mx.io.NDArrayIter(X, y, batch_size=BATCH)
    mod = mx.mod.Module(_mlp(0.0), context=mx.cpu())
    mon = mx.Monitor(interval=10**9, pattern="$^")  # forces staged path
    mod.fit(it, num_epoch=2, monitor=mon, optimizer="sgd",
            arg_params={k: v.copy() for k, v in _init_args().items()},
            optimizer_params={"learning_rate": 0.05, "momentum": 0.9},
            checkpoint=None, resume=d)
    assert not mod._fused_armed
    # momentum state landed in the updater, param-shaped
    states = mod._updater.states
    assert any(st is not None for st in states.values())


# ------------------------------------------------- manager contracts
def _tiny_module():
    X, y = _data()
    it = mx.io.NDArrayIter(X, y, batch_size=BATCH)
    mod = mx.mod.Module(_mlp(0.0), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(arg_params=_init_args(), aux_params={})
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.05),))
    return mod


def test_manager_atomic_commit_retention(tmp_path):
    d = str(tmp_path / "ck")
    mod = _tiny_module()
    mgr = mx.checkpoint.CheckpointManager(d, keep_last=2,
                                          async_write=False)
    for i in range(5):
        mgr.save(mod, epoch=0, nbatch=i)
    mgr.close()
    committed = mx.checkpoint.manager._committed(d)
    assert [s for s, _ in committed] == [4, 5]      # keep_last=2
    assert mx.checkpoint.latest_checkpoint(d)[0] == 5
    # no staging leftovers after clean commits
    assert not [n for n in os.listdir(d) if n.startswith(".tmp-")]
    # an incomplete dir is invisible to readers and a fresh manager
    # numbers past the committed history
    os.makedirs(os.path.join(d, "ckpt-00000099"))
    assert mx.checkpoint.latest_checkpoint(d)[0] == 5
    mgr2 = mx.checkpoint.CheckpointManager(d, async_write=False)
    assert mgr2._seq == 6
    mgr2.close()


def test_manager_async_commits_and_wait(tmp_path):
    d = str(tmp_path / "ck")
    mod = _tiny_module()
    with mx.checkpoint.CheckpointManager(d, async_write=True) as mgr:
        mgr.save(mod, 0, 1)
        mgr.save(mod, 0, 2)
        mgr.wait()
        assert len(mgr.list_committed()) == 2
    # restore round-trips through the async-written files
    cursor = mx.checkpoint.restore_module(_tiny_module(), d)
    assert cursor == {"epoch": 0, "nbatch": 2}


def test_restore_empty_dir_returns_none(tmp_path):
    assert mx.checkpoint.restore_module(_tiny_module(),
                                        str(tmp_path / "none")) is None


def test_checkpoint_env_surface(tmp_path, monkeypatch):
    """MXNET_CKPT_DIR alone turns checkpointing on in fit; EVERY and
    KEEP_LAST configure cadence/retention."""
    d = str(tmp_path / "envck")
    monkeypatch.setenv("MXNET_CKPT_DIR", d)
    monkeypatch.setenv("MXNET_CKPT_EVERY", "2")
    monkeypatch.setenv("MXNET_CKPT_KEEP_LAST", "2")
    _run(num_epoch=1, ckpt=None)
    committed = mx.checkpoint.manager._committed(d)
    assert len(committed) == 2                       # retention applied
    latest = mx.checkpoint.latest_checkpoint(d)
    with open(os.path.join(latest[1], "manifest.json")) as f:
        cursor = json.load(f)["cursor"]
    assert (cursor["epoch"], cursor["nbatch"]) == (1, 0)  # epoch-end save


def test_checkpoint_telemetry(tmp_path):
    d = str(tmp_path / "ck")
    mx.telemetry.enable()
    try:
        mx.telemetry.clear()
        mx.telemetry.flightrec.clear()
        _run(num_epoch=1, ckpt=d, every=2)
        snap = mx.telemetry.snapshot()
        assert snap["counters"].get("ckpt.snapshots", 0) >= 3
        assert snap["counters"].get("ckpt.commits", 0) >= 3
        assert "ckpt.exposed_stall.seconds" in snap["histograms"]
        assert "ckpt.snapshot.seconds" in snap["histograms"]
        kinds = {r["kind"] for r in mx.telemetry.flightrec.get_records()}
        assert "ckpt.snapshot" in kinds and "ckpt.commit" in kinds
    finally:
        mx.telemetry.disable()
        mx.telemetry.clear()


# -------------------------------------- optimizer-state layout transport
@pytest.mark.parametrize("optimizer", ["sgd", "adam"])
def test_save_checkpoint_states_across_layouts(tmp_path, optimizer):
    """Satellite: ``Module.save_checkpoint(save_optimizer_states=True)``
    under the fused plan restores SGD-momentum and Adam state
    bit-for-bit — into a fused module AND into a ZeRO-sharded one
    (the layout-independent transport), with update counts intact."""
    prefix = str(tmp_path / "ck")
    pa, _, ma = _run(num_epoch=1, optimizer=optimizer, dropout=0.0)
    ma.save_checkpoint(prefix, 1, save_optimizer_states=True)
    sa = _fused_states_np(ma)

    def fresh(zero_stage=None, n_dev=1):
        X, y = _data()
        it = mx.io.NDArrayIter(X, y, batch_size=BATCH)
        mod = mx.mod.Module.load(prefix, 1, load_optimizer_states=True,
                                 context=mx.cpu() if n_dev == 1 else
                                 [mx.cpu(i) for i in range(n_dev)])
        mod.bind(data_shapes=it.provide_data,
                 label_shapes=it.provide_label)
        mod.init_params(arg_params=mod._arg_params,
                        aux_params=mod._aux_params)
        if zero_stage:
            mod._zero_stage = zero_stage
        mod.init_optimizer(optimizer=optimizer,
                           optimizer_params=(("learning_rate", 0.05),)
                           if optimizer == "adam" else
                           (("learning_rate", 0.05), ("momentum", 0.9)))
        return mod

    # fused replicated
    mb = fresh()
    assert mb._fused_armed
    _assert_states_equal(sa, _fused_states_np(mb))
    assert mb._optimizer.num_update == ma._optimizer.num_update
    assert dict(mb._optimizer._index_update_count) == \
        dict(ma._optimizer._index_update_count)
    # ZeRO-1 sharded layout on a 2-device mesh
    mz = fresh(zero_stage=1, n_dev=2)
    assert mz._exec_group._state_layout is not None
    _assert_states_equal(sa, _fused_states_np(mz))


def test_legacy_states_file_still_loads(tmp_path):
    """Pre-format-2 ``.states`` pickles (bare states dict) load without
    counts — backward compatibility for old checkpoints."""
    _, _, ma = _run(num_epoch=1, optimizer="sgd", dropout=0.0)
    legacy = str(tmp_path / "legacy.states")
    with open(legacy, "wb") as f:
        pickle.dump({"__fused__": ma._exec_group.export_fused_states()},
                    f)
    _, _, mb = _run(num_epoch=1, optimizer="sgd", dropout=0.0)
    mb.load_optimizer_states(legacy)
    _assert_states_equal(_fused_states_np(ma), _fused_states_np(mb))


# ------------------------------------------------------------ rng chain
def test_random_state_roundtrip():
    mx.random.seed(42)
    st = mx.random.get_state()
    seq_a = [np.asarray(mx.random.next_key()) for _ in range(3)]
    mx.random.seed(7)                      # diverge
    mx.random.set_state(st)
    seq_b = [np.asarray(mx.random.next_key()) for _ in range(3)]
    for a, b in zip(seq_a, seq_b):
        np.testing.assert_array_equal(a, b)


def test_set_state_bumps_generation():
    g0 = mx.random.generation()
    mx.random.set_state(mx.random.get_state())
    assert mx.random.generation() == g0 + 1


# ---------------------------------------------------- kvstore seam bits
def test_kvstore_close_idempotent():
    kv = mx.kv.create("local")
    kv.close()
    kv.close()                              # second close: no-op
    kv2 = mx.kv.create("device")
    kv2.close(abort=True)
    kv2.close()
    assert kv.get_dead_nodes() == []
    assert kv.on_dead_node(lambda dead: None) is False  # no peers


def test_scheduler_drop_pending():
    from mxnet_tpu.kvstore_sched import BucketScheduler
    applied = []
    sched = BucketScheduler(lambda flat: flat,
                            lambda k, ctx, red: applied.append(k),
                            lambda: 1 << 30)
    sched.stage("w0", None, np.zeros(8, np.float32), 0)
    assert sched.drop_pending() == 1
    sched.flush()
    assert applied == []                    # dropped, never applied


# --------------------------------------------------- recovery plumbing
def test_survivor_env_remapping():
    base = {"DMLC_NUM_WORKER": "4", "DMLC_WORKER_ID": "2",
            "DMLC_PS_ROOT_URI": "127.0.0.1",
            "DMLC_PS_ROOT_PORT": "9300"}
    env = mx.checkpoint.survivor_env([1], env=base)
    assert env["DMLC_NUM_WORKER"] == "3"
    assert env["DMLC_WORKER_ID"] == "1"     # survivors [0,2,3] -> idx 1
    assert env["DMLC_PS_ROOT_PORT"] == "9301"
    assert env["MXNET_RECOVERY_GENERATION"] == "1"
    assert env["MXNET_RECOVERY_DEAD_RANKS"] == "1"
    # a second failure bumps the generation off the ORIGINAL base port
    env2 = mx.checkpoint.survivor_env([2], env=env)
    assert env2["DMLC_NUM_WORKER"] == "2"
    assert env2["DMLC_WORKER_ID"] == "1"    # old rank 2 -> 1 -> stays 1
    assert env2["DMLC_PS_ROOT_PORT"] == "9302"
    assert env2["MXNET_RECOVERY_GENERATION"] == "2"


def test_survivor_env_rejects_bad_sets():
    base = {"DMLC_NUM_WORKER": "2", "DMLC_WORKER_ID": "0",
            "DMLC_PS_ROOT_PORT": "9300"}
    with pytest.raises(mx.MXNetError):
        mx.checkpoint.survivor_env([], env=base)
    with pytest.raises(mx.MXNetError):
        mx.checkpoint.survivor_env([5], env=base)
    with pytest.raises(mx.MXNetError):     # the dead have no survivor env
        mx.checkpoint.survivor_env([0], env=base)


def test_dead_worker_error_shape():
    e = mx.checkpoint.DeadWorkerError([3, 1], clean=False)
    assert e.dead_ranks == [1, 3] and e.clean is False
    assert "committed checkpoint" in str(e)


# ------------------------------------------------------------- diagnose
def _diagnose():
    import importlib.util
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "diagnose_ckpt_test", os.path.join(root, "tools", "diagnose.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_diagnose_checkpoint_section_jsonl(tmp_path):
    """A checkpointed fit's live jsonl log renders the checkpoint
    section: snapshot/commit counts and the stall/write costs."""
    diagnose = _diagnose()
    d = str(tmp_path / "ck")
    mx.telemetry.enable()
    try:
        mx.telemetry.clear()
        mx.telemetry.metrics.reset()
        _run(num_epoch=1, ckpt=d, every=2)
        log = tmp_path / "ckpt.jsonl"
        mx.telemetry.jsonl.dump(str(log))
    finally:
        mx.telemetry.disable()
        mx.telemetry.clear()
        mx.telemetry.metrics.reset()
    out = diagnose.render_file(str(log))
    assert "checkpoint / recovery:" in out
    assert "committed" in out
    assert "exposed stall" in out
    assert "background write" in out


def test_diagnose_recovery_timeline_crash_path():
    """A crash report whose ring carries ckpt.commit + recovery records
    renders the recovery timeline (the post-mortem a dead-worker event
    leaves behind)."""
    diagnose = _diagnose()
    report = {
        "type": "crash_report", "time": "t", "pid": 1,
        "where": "module.fit",
        "exception": {"type": "DeadWorkerError", "message": "worker 2"},
        "metrics": {"counters": {"ckpt.snapshots": 4, "ckpt.commits": 4,
                                 "recovery.events": 1},
                    "gauges": {"ckpt.last_seq": 4.0},
                    "histograms": {}},
        "ring": [
            {"kind": "ckpt.commit", "ts_us": 1000, "seq": 3, "epoch": 1,
             "nbatch": 2},
            {"kind": "ckpt.commit", "ts_us": 5000000, "seq": 4,
             "epoch": 1, "nbatch": 4},
            {"kind": "recovery.dead_node", "ts_us": 6000000,
             "ranks": [2]},
            {"kind": "recovery.reexec", "ts_us": 7000000, "dead": [2],
             "generation": "1", "new_rank": "0", "new_nworker": "2"},
        ],
    }
    out = diagnose.render_crash(report)
    assert "checkpoint / recovery:" in out
    assert "RECOVERY: 1 dead-node detection(s)" in out
    assert "recovery.dead_node" in out and "recovery.reexec" in out
    assert "last commit: seq 4 at epoch 1, batch 4" in out


# ------------------------------------------------- resume-from-damage matrix
def _damage_truncate_pickle(path):
    with open(os.path.join(path, "state.pkl"), "r+b") as f:
        f.truncate(12)


def _damage_corrupt_pickle(path):
    p = os.path.join(path, "state.pkl")
    size = os.path.getsize(p)
    with open(p, "r+b") as f:
        f.seek(size // 2)
        f.write(b"\xde\xad\xbe\xef" * 8)


def _damage_missing_state(path):
    os.remove(os.path.join(path, "state.pkl"))


def _damage_missing_manifest(path):
    # an interrupted retention delete / fs repair can leave a ckpt-*
    # dir without its manifest: it must simply not count as committed
    os.remove(os.path.join(path, "manifest.json"))


@pytest.mark.parametrize("damage", [
    _damage_truncate_pickle, _damage_corrupt_pickle,
    _damage_missing_state, _damage_missing_manifest,
], ids=["truncated", "corrupt", "no-state", "no-manifest"])
def test_resume_falls_back_past_damaged_newest(tmp_path, damage):
    """The damage matrix (ISSUE 10 satellite): whatever happened to the
    newest checkpoint dir — truncated pickle, corrupt bytes, missing
    state.pkl, missing manifest — fit(resume=...) falls back to the
    previous commit with a warning, never crashes, and never loads a
    partial state."""
    d = str(tmp_path / "ck")
    _run(kill_at=(0, 5), ckpt=d, every=2)   # commits at cursors 2 and 4
    committed = mx.checkpoint.CheckpointManager(d).list_committed()
    assert len(committed) == 2
    damage(committed[-1][1])
    _, ac, _ = _run(ckpt=d, resume=True)
    # resumed from the PREVIOUS commit (cursor 2), not the damaged one
    assert ac[0][:2] == (0, 2)


def test_resume_all_damaged_starts_fresh(tmp_path):
    """Every commit unreadable -> resume warns and trains from scratch
    (cursor None), exactly like an empty directory — never a crash."""
    d = str(tmp_path / "ck")
    _run(kill_at=(0, 5), ckpt=d, every=2)
    mgr = mx.checkpoint.CheckpointManager(d)
    for _seq, path in mgr.list_committed():
        _damage_truncate_pickle(path)
    from mxnet_tpu.telemetry import metrics as _metrics
    before = _metrics.get_metric("ckpt.damaged")
    before = before.value if before else 0
    _, ac, _ = _run(ckpt=d, resume=True)
    assert ac[0][:2] == (0, 0)              # fresh start
    assert _metrics.get_metric("ckpt.damaged").value >= before + 2


def test_quarantined_seq_numbering_continues(tmp_path):
    """A quarantined seq stays burned: later commits use later seqs, so
    a half-written seq can never be confused with a committed one."""
    from mxnet_tpu import faults
    d = str(tmp_path / "ck")
    pol = faults.RetryPolicy(attempts=2, base_s=0, jitter=0)
    mgr = mx.checkpoint.CheckpointManager(d, retry_policy=pol)
    _, _, mod = _run(num_epoch=1)
    with faults.scope("ckpt.write:always"):
        bad = mgr.save(mod, 0, 1)
        with pytest.raises(Exception):
            mgr.wait()          # inside the scope: the writer thread
                                # must see the armed plane
    good = mgr.save(mod, 0, 2, block=True)
    assert good == bad + 1
    assert [s for s, _ in mgr.list_committed()] == [good]
    mgr.close()
