"""Failure-detection worker: the last rank dies; survivors must see it.

reference: tests/nightly's failure path + kvstore_dist.h:159-168
(GetDeadNodes over ps-lite heartbeats). Here the coordination service is
the failure detector: a peer that stops heartbeating (or whose connection
drops) shows up in get_num_dead_node() on every surviving rank.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import jax

jax.config.update("jax_platforms", "cpu")

os.environ.setdefault("PS_HEARTBEAT_TIMEOUT", "5")
os.environ["MXNET_KVSTORE_RECOVERABLE"] = "1"   # survive the peer death

import mxnet_tpu as mx  # noqa: E402


def main():
    from mxnet_tpu.kvstore import _coordination_client
    kv = mx.kv.create("dist_sync")
    rank, nworker = kv.rank, kv.num_workers
    # rendezvous through the coordination service, NOT a gloo collective
    # (kv._barrier): the doomed rank exits the moment its barrier call
    # returns, and tearing down gloo connections while a peer's
    # collective is still in flight aborts that peer before it can
    # observe the death
    _coordination_client().wait_at_barrier("dead_node_ready", 60_000)
    if rank == nworker - 1:
        os._exit(17)                 # die without shutdown: the failure
    dead = 0
    for _ in range(30):              # detector needs a beat to notice
        time.sleep(1)
        dead = kv.get_num_dead_node()
        if dead > 0:
            break
    print(f"DEAD_NODE_SEEN rank={rank} dead={dead}", flush=True)
    # survivors rendezvous (subset barrier: the dead rank excluded)
    # before exiting — rank 0 hosts the coordination service, and its
    # exit would kill the other survivors' detection mid-flight
    _coordination_client().wait_at_barrier(
        "dead_node_done", 60_000, list(range(nworker - 1)))
    if rank == 0:
        # rank 0 hosts the coordination service: linger so the other
        # survivors reach their os._exit before the coordinator vanishes
        # (a socket close mid-exit would abort them with rc!=0)
        time.sleep(3)
    # exit without the shutdown barrier: the dead peer would fail it, and
    # the point of this gate is the detection, not a clean teardown
    os._exit(0 if dead > 0 else 1)


if __name__ == "__main__":
    main()
