"""Decode fast paths (ISSUE 18): chunked prefill, prefix-cache reuse,
speculative decoding.

Pins the tentpole's correctness contracts: chunked prefill is bit-exact
against token-at-a-time greedy at every chunk size (windowed S>1 cache
writes land the same bytes), prefix-cache joins restore rows bitwise
equal to a cold prefill, speculative decoding never emits a token the
target wouldn't sample (and is bit-identical to target-only decode
under greedy, even with a DIFFERENT draft model), sampled decode
replays byte-deterministically on a recorded per-request rng chain
across rung migrations, and the zero-steady-state-compile gate holds
with all three fast paths armed across join/leave at every rung.
Satellites ride along: the ttft/ttft_exec split, ``serve.decode.
prefill`` trace spans per chunk, memplan's prefix-store charge + ME801
on a toy budget, and PK9xx coverage of the S>1 window spec.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.models import transformer as tfm
from mxnet_tpu.serve import FakeClock, PrefixStore, SamplingParams
from mxnet_tpu.serve.sampling import (sample_token, speculative_verify,
                                      token_probs)

V, D, L, H, T = 64, 32, 2, 4, 32      # tiny LM; T doubles as capacity


def _train_params(d_model, n_layer, seed):
    np.random.seed(seed)
    sym = tfm.get_symbol(vocab_size=V, d_model=d_model, n_layer=n_layer,
                         n_head=H, seq_len=8, include_loss=False,
                         max_seq_len=T)
    mod = mx.mod.Module(sym, label_names=[])
    mod.bind([("data", (1, 8))], None, for_training=False)
    mod.init_params(mx.initializer.Xavier(rnd_type="gaussian",
                                          magnitude=2))
    args, _ = mod.get_params()
    return {k: v.asnumpy() for k, v in args.items()}


@pytest.fixture(scope="module")
def target_params():
    return _train_params(D, L, seed=0)


@pytest.fixture(scope="module")
def draft_params():
    """A genuinely different (smaller) draft model: the rejection rule
    must keep greedy output identical anyway."""
    return _train_params(D, 1, seed=1)


def _nd(params):
    return {k: mx.nd.array(v) for k, v in params.items()}


def _gen(d_model=D, n_layer=L):
    return lambda s: tfm.get_decode_symbol(
        vocab_size=V, d_model=d_model, n_layer=n_layer, n_head=H,
        capacity=T, per_slot=True, step_len=s, max_seq_len=T)


_names = [0]


def _sched(target_params, ladder=(1, 2, 4), chunk=1, draft=None,
           spec_k=None, prefix_mb=0, clock=None, **kw):
    _names[0] += 1
    gen = _gen()
    return mx.serve.serve_decoder(
        gen(1), _nd(target_params), name=f"fast{_names[0]}", capacity=T,
        ladder=list(ladder), clock=clock or FakeClock(), start=False,
        symbol_gen=gen if (chunk > 1 or draft is not None) else None,
        prefill_chunk=chunk,
        draft_symbol_gen=_gen(n_layer=1) if draft is not None else None,
        draft_params=_nd(draft) if draft is not None else None,
        spec_k=spec_k, prefix_cache_mb=prefix_mb, **kw)


def _ref_greedy(params, prompt, n):
    """Token-at-a-time greedy through the scalar KVCacheDecoder — the
    PR-15 reference path every fast path must reproduce bitwise."""
    m = mx.mod.Module(
        tfm.get_decode_symbol(vocab_size=V, d_model=D, n_layer=L,
                              n_head=H, capacity=T, max_seq_len=T),
        label_names=[])
    m.bind([("data", (1, 1))], None, for_training=False)
    m.init_params(initializer=None, arg_params=_nd(params),
                  aux_params={}, allow_missing=True)
    d = tfm.KVCacheDecoder(m, capacity=T)
    for t in prompt[:-1]:
        d.step(np.asarray([[t]], np.int32))
    cur, out = int(prompt[-1]), []
    for _ in range(n):
        lg = d.step(np.asarray([[cur]], np.int32)).asnumpy()[0, 0]
        cur = int(np.argmax(lg))
        out.append(cur)
    return out


def _prompts(seed, n, lo=2, hi=12):
    rs = np.random.RandomState(seed)
    return [rs.randint(1, V, rs.randint(lo, hi)).tolist()
            for _ in range(n)]


# ===================================================== chunked prefill
@pytest.mark.parametrize("chunk", [2, 3, 5, 8, 16])
def test_chunked_prefill_bit_exact_every_chunk_size(target_params,
                                                    chunk):
    """Acceptance: greedy output under chunked prefill is bit-identical
    to the token-at-a-time PR-15 path at every chunk size, including
    sizes that don't divide the prompt (padded final chunk + rewind)."""
    sched = _sched(target_params, ladder=(1, 2), chunk=chunk)
    prompts = _prompts(10 + chunk, 3)
    hs = [sched.submit(p, max_new_tokens=6) for p in prompts]
    sched.pump()
    outs = [list(h.result(timeout=5)) for h in hs]
    # stats snapshot NOW: compile_count is process-global and the
    # reference decoders below compile their own programs
    st = sched.stats()
    for p, out in zip(prompts, outs):
        assert out == _ref_greedy(target_params, p, 6)
    assert st["compiles_since_warmup"] == 0
    assert st["prefill_chunks"] >= 3


def test_chunked_prefill_mixed_decode_slots_ride_along(target_params):
    """A slot mid-decode rides a batchmate's chunk dispatch with one
    real token + pads and rewinds after — its stream is unchanged."""
    sched = _sched(target_params, ladder=(2,), chunk=8)
    a = [3, 5, 7]
    b = list(np.random.RandomState(2).randint(1, V, 20))
    ha = sched.submit(a, max_new_tokens=10)
    sched.pump(max_iterations=2)          # a reaches steady state
    hb = sched.submit(b, max_new_tokens=4)
    sched.pump()
    out_a = list(ha.result(timeout=5))
    out_b = list(hb.result(timeout=5))
    st = sched.stats()
    assert out_a == _ref_greedy(target_params, a, 10)
    assert out_b == _ref_greedy(target_params, b, 4)
    assert st["compiles_since_warmup"] == 0


def test_chunk_dispatch_count_and_prefill_spans(target_params):
    """A T-token prompt prefills in ceil(T/S) window dispatches, each
    recording one ``serve.decode.prefill`` span."""
    from mxnet_tpu.telemetry import trace as _trace
    _trace.clear()
    _trace.configure(sample=1)
    try:
        sched = _sched(target_params, ladder=(1,), chunk=8)
        prompt = list(np.random.RandomState(3).randint(1, V, 20))
        h = sched.submit(prompt, max_new_tokens=2)
        sched.pump()
        h.result(timeout=5)
        spans = [s for s in _trace.spans(h.trace_id)
                 if s["name"] == "serve.decode.prefill"]
        # 20 prompt tokens in chunks of 8 -> 8 + 8 + 4 dispatches (the
        # final chunk's last row doubles as the first sampling feed)
        assert len(spans) == 3
        assert sorted(s["tokens"] for s in spans) == [4, 8, 8]
        assert {s["chunk"] for s in spans} == {8}
    finally:
        _trace.configure(sample=_trace._env_sample(), reset_ids=False)


def test_ttft_and_ttft_exec_split(target_params):
    """Bugfix satellite: ``ttft`` counts from submit (queue wait
    included), ``ttft_exec`` from the first dispatch that covered the
    sequence — under queueing they must differ."""
    clock = FakeClock()
    sched = _sched(target_params, ladder=(1,), chunk=4, clock=clock)
    p1 = list(range(2, 8))
    h1 = sched.submit(p1, max_new_tokens=2)
    h2 = sched.submit(p1, max_new_tokens=2)   # queued behind h1
    assert h1.ttft is None and h1.ttft_exec is None
    while not h2.done():
        clock.advance(0.01)
        sched.pump(max_iterations=1)
    assert h1.ttft is not None and h1.ttft_exec is not None
    assert h1.ttft >= h1.ttft_exec
    # h2 sat in the queue while h1 decoded: wait shows up only in ttft
    assert h2.ttft - h2.ttft_exec > h1.ttft - h1.ttft_exec
    assert h2.ttft > h2.ttft_exec


# ===================================================== sampled decode
def test_sampling_filters_and_greedy_draws():
    rs = np.random.RandomState(0)
    logits = rs.randn(V).astype(np.float32)
    g = token_probs(logits, SamplingParams())
    assert g[int(np.argmax(logits))] == 1.0 and g.sum() == 1.0
    k3 = token_probs(logits, SamplingParams(temperature=1.0, top_k=3))
    assert (k3 > 0).sum() == 3 and abs(k3.sum() - 1.0) < 1e-12
    assert set(np.nonzero(k3)[0]) == set(np.argsort(-logits)[:3])
    p = SamplingParams(temperature=0.7, top_p=0.5)
    tp = token_probs(logits, p)
    full = token_probs(logits, SamplingParams(temperature=0.7))
    kept = np.nonzero(tp)[0]
    # minimal prefix: kept mass >= 0.5, dropping the smallest kept
    # token goes under
    assert full[kept].sum() >= 0.5
    assert full[kept].sum() - full[kept].min() < 0.5
    # greedy consumes NO rng draws
    rng = SamplingParams().make_rng()
    sample_token(logits, SamplingParams(), rng)
    assert rng.random() == SamplingParams().make_rng().random()
    with pytest.raises(mx.base.MXNetError):
        SamplingParams(temperature=-1)
    with pytest.raises(mx.base.MXNetError):
        SamplingParams(top_p=0.0)


def test_sampled_decode_byte_deterministic_replay(target_params):
    """Acceptance: a sampled run replays byte-for-byte given the same
    seeds — across staggered arrivals forcing rung migrations — and a
    different seed diverges."""
    def run(seed):
        sched = _sched(target_params, ladder=(1, 2, 4), chunk=4)
        prompts = _prompts(20, 5, lo=3, hi=10)
        hs = []
        for i, p in enumerate(prompts):
            hs.append(sched.submit(
                p, max_new_tokens=6,
                sampling=SamplingParams(temperature=0.9, top_k=20,
                                        top_p=0.95, seed=seed + i)))
            sched.pump(max_iterations=1 + i % 2)
        sched.pump()
        st = sched.stats()
        return [list(h.result(timeout=5)) for h in hs], st

    outs1, st1 = run(100)
    outs2, _ = run(100)
    assert outs1 == outs2                     # byte-deterministic
    assert st1["compiles_since_warmup"] == 0
    assert st1["migrations"] >= 1             # replay spans migrations
    outs3, _ = run(999)
    assert outs3 != outs1                     # the chain is the seed


# ================================================== speculative decode
def test_spec_verify_never_emits_untargeted_token():
    """The rejection rule's safety contract: every emitted token has
    nonzero target probability, accepted prefixes match proposals, and
    a rejection ends the window with a residual-sampled token."""
    rs = np.random.RandomState(5)
    params = SamplingParams(temperature=1.0, seed=7)
    for _ in range(50):
        K = rs.randint(1, 5)
        t_rows = rs.randn(K, V).astype(np.float32) * 3
        d_rows = rs.randn(K, V).astype(np.float32) * 3
        props = [sample_token(d_rows[j], params,
                              SamplingParams(seed=rs.randint(9)).
                              make_rng()) for j in range(K)]
        acc, toks = speculative_verify(t_rows, d_rows, props, params,
                                       params.make_rng())
        assert 0 <= acc <= K and 1 <= len(toks) <= K
        assert toks[:acc] == props[:acc]
        for j, tok in enumerate(toks):
            assert token_probs(t_rows[j], params)[tok] > 0.0
        if acc < K:
            assert len(toks) == acc + 1
    # greedy degeneracy: accept while argmaxes agree, then emit the
    # target argmax
    t_rows = rs.randn(3, V).astype(np.float32)
    d_rows = t_rows.copy()
    d_rows[1] += np.eye(V, dtype=np.float32)[0] * 100   # diverge at j=1
    g = SamplingParams()
    props = [int(np.argmax(r)) for r in d_rows]
    acc, toks = speculative_verify(t_rows, d_rows, props, g,
                                   g.make_rng())
    assert acc == 1 and toks == [int(np.argmax(t_rows[0])),
                                 int(np.argmax(t_rows[1]))]


def test_spec_greedy_bit_identical_with_foreign_draft(target_params,
                                                      draft_params):
    """Acceptance: greedy output with speculation armed (draft = a
    DIFFERENT model) is bit-identical to the PR-15 token-at-a-time
    path, at staggered per-slot positions, with zero steady-state
    compiles and live acceptance telemetry."""
    sched = _sched(target_params, ladder=(1, 2, 4), chunk=4,
                   draft=draft_params, spec_k=3)
    prompts = _prompts(30, 5, lo=2, hi=9)
    hs = []
    for i, p in enumerate(prompts):      # staggered: slots at
        hs.append(sched.submit(p, max_new_tokens=7))   # different pos
        sched.pump(max_iterations=1 + i % 2)
    sched.pump()
    outs = [list(h.result(timeout=5)) for h in hs]
    st = sched.stats()
    for p, out in zip(prompts, outs):
        assert out == _ref_greedy(target_params, p, 7)
    assert st["compiles_since_warmup"] == 0
    assert st["spec"]["k"] == 3
    assert st["spec"]["proposed"] > 0
    assert st["spec"]["acceptance"] is not None
    assert st["spec"]["rollbacks"] >= 0


def test_spec_self_draft_accepts_everything(target_params):
    """Draft == target weights: every proposal verifies, acceptance is
    1.0 and no rollbacks happen — the acceptance-telemetry fixture."""
    draft = {k: v for k, v in target_params.items()}
    _names[0] += 1
    gen = _gen()
    sched = mx.serve.serve_decoder(
        gen(1), _nd(target_params), name=f"fast{_names[0]}", capacity=T,
        ladder=[1], clock=FakeClock(), start=False, symbol_gen=gen,
        prefill_chunk=1, draft_symbol_gen=gen, draft_params=_nd(draft),
        spec_k=4, prefix_cache_mb=0)
    p = [2, 9, 4]
    h = sched.submit(p, max_new_tokens=8)
    sched.pump()
    assert list(h.result(timeout=5)) == _ref_greedy(target_params, p, 8)
    st = sched.stats()["spec"]
    assert st["acceptance"] == 1.0 and st["rollbacks"] == 0
    # 8 tokens in ceil(8/4)=2 speculative iterations after prefill
    assert st["proposed"] == 8


def test_spec_validation_errors(target_params, draft_params):
    with pytest.raises(mx.base.MXNetError, match="draft_params"):
        mx.serve.serve_decoder(_gen()(1), _nd(target_params),
                               draft_symbol_gen=_gen(n_layer=1))
    with pytest.raises(mx.base.MXNetError, match="symbol_gen"):
        mx.serve.serve_decoder(_gen()(1), _nd(target_params),
                               draft_symbol_gen=_gen(n_layer=1),
                               draft_params=_nd(draft_params))


# ================================================== prefix-cache reuse
def test_prefix_join_rows_bitwise_equal_cold_prefill(target_params):
    """Acceptance: the rows a prefix hit restores are bitwise the rows
    a cold token-at-a-time prefill writes, and the warm sequence's
    output is identical."""
    sched = _sched(target_params, ladder=(1,), chunk=4, prefix_mb=4)
    prompt = list(np.random.RandomState(8).randint(1, V, 11))
    h_cold = sched.submit(prompt, max_new_tokens=5, prefix_id="sys")
    sched.pump()
    cold = list(h_cold.result(timeout=5))
    store = sched.prefix_store
    assert len(store) == 1 and store.misses == 1

    # warm join: same output, hit counted, zero steady-state compiles
    h_warm = sched.submit(prompt, max_new_tokens=5, prefix_id="sys")
    sched.pump()
    warm = list(h_warm.result(timeout=5))
    st = sched.stats()            # snapshot before the refs compile
    assert warm == cold
    assert store.hits >= 1
    assert st["prefix"]["hit_rate"] > 0
    assert st["compiles_since_warmup"] == 0

    assert cold == _ref_greedy(target_params, prompt, 5)
    # bitwise reference: a cold prefill of the SAME configuration in a
    # fresh scheduler — the stored rows are exactly what it writes
    # (decode only touches positions past the prompt, so the slot's
    # first len(prompt) rows still hold the prefill bytes)
    sched2 = _sched(target_params, ladder=(1,), chunk=4, prefix_mb=0)
    h2 = sched2.submit(prompt, max_new_tokens=5)
    sched2.pump()
    assert list(h2.result(timeout=5)) == cold
    ref_rows = sched2.engine.driver(1).capture_rows(0, len(prompt))
    entry = store.lookup("sys", np.asarray(prompt + [0]),
                         tags=("target",))[1]
    assert entry is not None
    for nm, ref in ref_rows.items():
        assert np.array_equal(entry.payloads["target"][nm], ref), nm
    # and within float tolerance of the token-at-a-time path (XLA may
    # reduce the S>1 einsum in a different order — low bits only;
    # greedy OUTPUT equality above is the bit-exactness contract)
    eng = mx.serve.DecodeEngine(
        f"fastref{_names[0]}", _gen()(1), _nd(target_params),
        capacity=T, ladder=[1])
    drv = eng.driver(1)
    drv.join(0)
    for t in prompt:
        drv.step(np.asarray([[t]], np.int32))
    for nm, ref in drv.capture_rows(0, len(prompt)).items():
        assert np.allclose(entry.payloads["target"][nm], ref,
                           rtol=1e-4, atol=1e-5), nm


def test_prefix_store_lru_mismatch_and_budget():
    rows = {"target": {"c": np.zeros((2, 8, 4), np.float32)}}
    entry_bytes = 2 * 8 + 2 * 8 * 4 * 4       # 2 int64 tokens + rows
    store = PrefixStore(budget_bytes=3 * entry_bytes)
    assert store.put("a", [1, 2], rows)
    assert store.put("b", [3, 4], rows)
    assert store.put("c", [5, 6], rows)
    store.lookup("a", np.asarray([1, 2, 9]))          # refresh a's LRU
    assert store.put("d", [7, 8], rows)               # evicts b
    assert store.lookup("b", np.asarray([3, 4, 9]))[1] is None
    assert store.lookup("a", np.asarray([1, 2, 9]))[1] is not None
    assert store.evictions >= 1
    # token mismatch: a miss (and a tick), never a wrong join
    c, e = store.lookup("a", np.asarray([9, 9, 9]))
    assert e is None and store.mismatches == 1
    # a missing engine payload (draft armed later) is a miss
    assert store.lookup("a", np.asarray([1, 2, 9]),
                        tags=("target", "draft"))[1] is None
    # full-prompt hits cap at len(prompt) - 1: one token always left
    c, e = store.lookup("a", np.asarray([1, 2]))
    assert e is not None and c == 1
    # oversized entries are dropped whole
    tiny = PrefixStore(budget_bytes=8)
    assert not tiny.put("x", [1], rows)
    assert len(tiny) == 0


# ===================================== all three armed: zero compiles
def test_zero_compiles_all_fastpaths_across_every_rung(target_params,
                                                       draft_params):
    """Acceptance: compile_count() delta == 0 after warmup with
    chunking + prefix reuse + speculation all armed, across join/leave
    churn forcing migrations through every rung."""
    sched = _sched(target_params, ladder=(1, 2, 4), chunk=4,
                   draft=draft_params, spec_k=3, prefix_mb=4)
    mark = mx.program_cache.compile_count()
    rs = np.random.RandomState(11)
    hs = [sched.submit(rs.randint(1, V, 6).tolist(), max_new_tokens=3,
                       prefix_id="war")]
    sched.pump()
    hs += [sched.submit(rs.randint(1, V, 4 + i).tolist(),
                        max_new_tokens=3 + i,
                        sampling=SamplingParams(temperature=0.8,
                                                seed=i))
           for i in range(4)]
    sched.pump()
    for i in range(5):
        hs.append(sched.submit(rs.randint(1, V, 5).tolist(),
                               max_new_tokens=3,
                               prefix_id="war" if i % 2 else None))
        sched.pump(max_iterations=2)
    sched.pump()
    for h in hs:
        h.result(timeout=5)
    assert mx.program_cache.compile_count() - mark == 0
    assert sched.engine.compiles_since_warmup() == 0
    assert sched.draft.compiles_since_warmup() == 0
    assert sched.stats()["migrations"] >= 2
    assert sched.engine.programs_resident()
    assert sched.draft.programs_resident()
    # 3 rungs x (S=1 + chunk window + verify window) on the target
    assert len(sched.engine.program_keys()) == 9


def test_window_aux_cells_are_shared(target_params):
    """The S>1 window module advances the SAME device cache/cursor
    cells as the rung's S=1 module — the seam everything above rides."""
    eng = mx.serve.DecodeEngine(
        f"fastaux{_names[0]}", _gen()(1), _nd(target_params),
        capacity=T, ladder=[2], symbol_gen=_gen(), window_lens=(4,))
    base = eng._bm._buckets[2]._exec_group.executor
    win = eng._window_mods[(2, 4)]._exec_group.executor
    for nm, cell in base.aux_dict.items():
        assert win.aux_dict[nm] is cell, nm
    drv = eng.driver(2)
    assert drv.window_lens == [4]
    with pytest.raises(mx.base.MXNetError, match="window"):
        drv.step(np.zeros((2, 3), np.int32))   # no S=3 module


# ================================================= memplan satellites
def test_memplan_prefix_store_bytes_and_me801(target_params):
    """The prefix-store budget is charged as fixed device bytes on
    per-slot decode graphs (and ONLY there), and ME801 trips on a toy
    budget that fits the model but not model + store."""
    from mxnet_tpu.analysis import memplan
    sym = _gen()(1)
    plan0 = memplan.plan_symbol(sym, {"data": (2, 1)}, policy="none",
                                for_training=False)
    assert plan0["prefix_store_bytes"] == 0      # env unset -> uncharged
    budget = 1 << 20
    plan = memplan.plan_symbol(sym, {"data": (2, 1)}, policy="none",
                               for_training=False,
                               prefix_cache_bytes=budget)
    assert plan["prefix_store_bytes"] == budget
    assert plan["fixed_bytes"] == plan0["fixed_bytes"] + budget
    assert plan["per_op_bytes"].get("prefix_store") == budget
    # a non-decode graph never charges the store
    full = tfm.get_symbol(vocab_size=V, d_model=D, n_layer=1, n_head=H,
                          seq_len=8, include_loss=False)
    planf = memplan.plan_symbol(full, {"data": (2, 8)}, policy="none",
                                for_training=False,
                                prefix_cache_bytes=budget)
    assert planf["prefix_store_bytes"] == 0
    # ME801: fits without the store, trips with it
    cap = plan0["peak_bytes_per_device"] + budget // 2
    assert not any(d.rule == "ME801" for d in
                   memplan.plan_findings(plan0, capacity_bytes=cap))
    assert any(d.rule == "ME801" for d in
               memplan.plan_findings(plan, capacity_bytes=cap))


def test_memplan_prefix_env(monkeypatch, target_params):
    from mxnet_tpu.analysis import memplan
    monkeypatch.setenv("MXNET_SERVE_PREFIX_CACHE_MB", "2")
    plan = memplan.plan_symbol(_gen()(1), {"data": (2, 1)},
                               policy="none", for_training=False)
    assert plan["prefix_store_bytes"] == 2 << 20


# ==================================================== PK9xx satellite
def test_attention_decode_window_kernel_spec():
    """PK9xx covers the S>1 window path: the declared tile set is
    VMEM-clean, lane/sublane aligned, and registration would refuse a
    misaligned one."""
    from mxnet_tpu.analysis.kernelcheck import validate_kernel_spec
    from mxnet_tpu.rtc import _ATTENTION_DECODE_KSPEC
    validate_kernel_spec("attention_decode", "window",
                         _ATTENTION_DECODE_KSPEC)   # idempotent: clean
    bad = dict(_ATTENTION_DECODE_KSPEC,
               tiles=[((64, 100), "float32")])      # lanes % 128 != 0
    with pytest.raises(mx.base.MXNetError, match="PK902"):
        validate_kernel_spec("attention_decode", "window", bad)
