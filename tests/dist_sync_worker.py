"""dist_sync arithmetic-invariant worker (launched N-way by launch.py).

Port of the reference nightly gate (reference:
tests/nightly/dist_sync_kvstore.py:1-47): after nrepeat synchronized
pushes from nworker workers, where worker w pushes ones*(w+1) and the
store runs the Test optimizer (w += rate * grad), every pulled value must
equal  (nworker+1)*nworker/2 * rate * nrepeat + 1  — including a large
key that spans multiple all-reduce buckets, proving the bucketed batched
collective preserves the per-key arithmetic.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import jax

# same platform forcing as tests/conftest.py: the site plugin ignores
# JAX_PLATFORMS, the config update does not
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import mxnet_tpu as mx  # noqa: E402


def check(val, expected):
    arr = val.asnumpy()
    assert np.allclose(arr, expected, rtol=1e-5), (arr.ravel()[:4], expected)


def main():
    # small bucket cap so the big key exercises multi-bucket batching
    os.environ["MXNET_KVSTORE_BUCKET_BYTES"] = str(1 << 18)   # 256 KiB
    kv = mx.kv.create("dist_sync")
    nworker = kv.num_workers
    rank = kv.rank
    rate = 2.0
    shapes = {3: (4, 4), 9: (4, 5), 99: (300, 300)}       # 99: 360 KB > cap
    kv.set_optimizer(mx.optimizer.create("test", rescale_grad=rate))
    for k, s in shapes.items():
        kv.init(k, mx.nd.ones(s))

    nrepeat = 3
    for _ in range(nrepeat):
        kv.push(list(shapes), [mx.nd.ones(s) * (rank + 1)
                               for s in shapes.values()])

    expected = (nworker + 1) * nworker / 2 * rate * nrepeat + 1
    for k, s in shapes.items():
        out = mx.nd.empty(s)
        kv.pull(k, out=out)
        check(out, expected)

    assert kv.get_num_dead_node(timeout_ms=5000) == 0
    kv._barrier()
    kv.close()                  # stop/join the heartbeat thread
    print(f"DIST_SYNC_OK rank={rank} nworker={nworker} "
          f"expected={expected}", flush=True)


if __name__ == "__main__":
    main()
    sys.exit(0)
