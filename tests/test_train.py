"""Metric-gated end-to-end training tests.

reference: tests/python/train/test_mlp.py:100 and test_conv.py — small
full-stack runs through Module.fit that must reach an accuracy
threshold; the convolution gate exercises Convolution/Pooling/BatchNorm
backward through a real optimizer, not just op-level numerics.
"""
import os
import sys

import numpy as np
import pytest

import mxnet_tpu as mx

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "examples"))
from common import data as exdata  # noqa: E402
from mxnet_tpu.models import mlp, lenet  # noqa: E402

pytestmark = pytest.mark.slow


def _fit_and_score(net, imgs, labels, batch_size=50, num_epoch=2,
                   lr=0.05, optimizer="sgd"):
    it = mx.io.NDArrayIter(imgs, labels, batch_size, shuffle=True)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, eval_metric="acc", optimizer=optimizer,
            optimizer_params={"learning_rate": lr, "momentum": 0.9,
                              "wd": 1e-4},
            num_epoch=num_epoch,
            initializer=mx.initializer.Xavier(rnd_type="gaussian",
                                              factor_type="in", magnitude=2))
    it.reset()
    return mod.score(it, "acc")[0][1], mod


def test_mlp_convergence_gate():
    """MNIST-style MLP must exceed 0.95 train accuracy (reference
    test_mlp.py gates at 0.9+ on real MNIST)."""
    imgs, labels = exdata.synthetic_classification(2000, (784,), 10, seed=1)
    acc, _ = _fit_and_score(mlp.get_symbol(10), imgs, labels)
    assert acc >= 0.95, f"MLP convergence gate failed: acc={acc}"


def test_conv_convergence_gate():
    """LeNet (Convolution+Pooling+FC) must exceed 0.95 — the convolution
    backward path trained to a gate (reference test_conv.py)."""
    imgs, labels = exdata.synthetic_classification(1500, (1, 28, 28), 10,
                                                   seed=2)
    acc, _ = _fit_and_score(lenet.get_symbol(10), imgs, labels,
                            num_epoch=3, lr=0.02)
    assert acc >= 0.95, f"LeNet convergence gate failed: acc={acc}"


def test_checkpoint_resume_continues_training():
    """do_checkpoint + fit(begin_epoch) resume path (reference
    common/fit.py --load-epoch)."""
    imgs, labels = exdata.synthetic_classification(600, (784,), 10, seed=3)
    it = mx.io.NDArrayIter(imgs, labels, 50, shuffle=True)
    net = mlp.get_symbol(10)
    prefix = os.path.join("/tmp", "mxtpu_resume_test")
    opt_params = {"learning_rate": 0.1, "momentum": 0.9}
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=1, optimizer="sgd", optimizer_params=opt_params,
            epoch_end_callback=mx.callback.do_checkpoint(prefix),
            initializer=mx.initializer.Uniform(0.05))
    it.reset()
    acc1 = mod.score(it, "acc")[0][1]
    sym2, args2, aux2 = mx.model.load_checkpoint(prefix, 1)
    # params round-trip exactly through the reference-format container
    a1, _ = mod.get_params()
    np.testing.assert_array_equal(a1["fc1_weight"].asnumpy(),
                                  args2["fc1_weight"].asnumpy())
    it.reset()
    mod2 = mx.mod.Module(sym2, context=mx.cpu())
    mod2.fit(it, num_epoch=6, begin_epoch=1, optimizer="sgd",
             optimizer_params=opt_params,
             arg_params=args2, aux_params=aux2)
    it.reset()
    acc = mod2.score(it, "acc")[0][1]
    assert acc >= max(acc1, 0.9), \
        f"resumed training underperformed: {acc1} -> {acc}"


@pytest.mark.parametrize("script,args", [
    ("lstm_bucketing.py", ["--num-epochs", "1", "--num-hidden", "32",
                           "--num-embed", "32", "--num-layers", "1"]),
    ("dcgan.py", ["--num-epochs", "1", "--batches-per-epoch", "4",
                  "--batch-size", "8"]),
    ("train_mnist.py", ["--num-epochs", "1", "--batch-size", "32",
                        "--network", "mlp"]),
    ("train_cifar10.py", ["--num-epochs", "1", "--batch-size", "16",
                          "--num-layers", "20", "--num-classes", "4"]),
    ("train_imagenet.py", ["--num-epochs", "1", "--batch-size", "8",
                           "--num-layers", "18", "--num-classes", "4",
                           "--num-examples", "32"]),
    ("train_imagenet.py", ["--num-epochs", "1", "--batch-size", "2",
                           "--network", "inception-v3", "--num-classes",
                           "4", "--num-examples", "4", "--num-val", "2"]),
    ("ssd/train.py", ["--epochs", "1", "--batch-size", "8",
                      "--num-images", "16", "--width", "8",
                      "--data-size", "64"]),
    ("bi_lstm_sort.py", ["--num-epochs", "1", "--num-train", "256",
                         "--seq-len", "6", "--num-hidden", "24"]),
    ("model_parallel_lstm.py", ["--num-epochs", "3"]),
])
def test_example_scripts_smoke(script, args):
    """Every shipped example must run end-to-end (tiny settings)."""
    import subprocess
    root = os.path.join(os.path.dirname(__file__), "..")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    res = subprocess.run(
        [sys.executable, os.path.join(root, "examples", script)] + args,
        capture_output=True, text=True, timeout=900, env=env, cwd=root)
    assert res.returncode == 0, \
        f"{script} failed:\n{res.stdout[-2000:]}\n{res.stderr[-2000:]}"


def test_mlp_real_data_convergence_gate():
    """Val-accuracy gate on REAL handwritten digits (scikit-learn's
    vendored UCI scans — see exdata.real_digits). Unlike the
    prototype-synthetic gates above, a subtly-wrong BatchNorm/momentum
    cannot pass this: generalization to held-out real scans is required.
    Reference: tests/python/train/test_mlp.py:88-100 (MNIST >= 0.9;
    gated here at 0.95 per BASELINE.md CI gates)."""
    tr_img, tr_lbl, va_img, va_lbl = exdata.real_digits(seed=0)
    it = mx.io.NDArrayIter(tr_img.reshape(len(tr_img), -1), tr_lbl, 50,
                           shuffle=True)
    vit = mx.io.NDArrayIter(va_img.reshape(len(va_img), -1), va_lbl, 50)
    mod = mx.mod.Module(mlp.get_symbol(10), context=mx.cpu())
    mod.fit(it, eval_data=vit, eval_metric="acc", optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                              "wd": 1e-4},
            num_epoch=10,
            initializer=mx.initializer.Xavier(rnd_type="gaussian",
                                              factor_type="in",
                                              magnitude=2))
    vit.reset()
    acc = mod.score(vit, "acc")[0][1]
    assert acc >= 0.95, f"real-data MLP val-acc gate failed: {acc}"


def test_cifar_scale_real_data_gate(tmp_path, monkeypatch):
    """CIFAR-scale gate on REAL photographs through the FULL pipeline:
    JPEG RecordIO pack -> multiprocess decode -> random-crop/mirror
    augmentation -> ResNet-8 (conv/BN trunk) -> NHWC execution pass ON.
    Real 32x32 RGB patches of scikit-learn's two vendored photos,
    labeled by source photo, with a SPATIAL train/val split (no tile
    overlap across it) — mis-normalized BatchNorm statistics, a broken
    augmenter, or a layout-pass bug all fail this gate.
    Reference: tests/nightly/test_all.sh:42-55 (CIFAR-10 conv >= 0.86);
    threshold tuned to this 2-class subset (observed ~0.94)."""
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import im2rec
    from mxnet_tpu import recordio
    from mxnet_tpu.models import resnet

    monkeypatch.setenv("MXNET_NHWC_LAYOUT", "1")
    tr, trl, va, val = exdata.real_photo_patches()

    def pack(prefix, imgs, lbls):
        rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec",
                                         "w")
        for i, (im, lb) in enumerate(zip(imgs, lbls)):
            rec.write_idx(i, recordio.pack(
                recordio.IRHeader(0, float(lb), i, 0),
                im2rec._encode(im, quality=95)))   # _encode takes RGB
        rec.close()
        return prefix

    trp = pack(str(tmp_path / "train"), tr, trl)
    vap = pack(str(tmp_path / "val"), va, val)
    kw = dict(mean_r=128, mean_g=128, mean_b=128, std_r=60, std_g=60,
              std_b=60, num_workers=2, prefetch=False)
    it = mx.image.ImageRecordIter(trp + ".rec", path_imgidx=trp + ".idx",
                                  data_shape=(3, 28, 28), batch_size=50,
                                  shuffle=True, rand_crop=True,
                                  rand_mirror=True, **kw)
    assert type(it).__name__ == "MPImageRecordIter"   # the MP decode path
    vit = mx.image.ImageRecordIter(vap + ".rec", path_imgidx=vap + ".idx",
                                   data_shape=(3, 28, 28), batch_size=50,
                                   **kw)
    net = resnet.get_symbol(num_classes=2, num_layers=8,
                            image_shape="3,28,28")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, eval_data=vit, eval_metric="acc", optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                              "wd": 1e-4},
            num_epoch=6,
            initializer=mx.initializer.Xavier(rnd_type="gaussian",
                                              factor_type="in",
                                              magnitude=2))
    vit.reset()
    acc = mod.score(vit, "acc")[0][1]
    it.close()
    vit.close()
    assert acc >= 0.88, f"real-photo CIFAR-scale gate failed: {acc}"


def test_conv_real_data_convergence_gate():
    """LeNet val-accuracy gate on real digit scans — convolution,
    pooling and BN backward trained against real image statistics
    (reference: tests/python/train/test_conv.py)."""
    tr_img, tr_lbl, va_img, va_lbl = exdata.real_digits(seed=0)
    it = mx.io.NDArrayIter(tr_img, tr_lbl, 50, shuffle=True)
    vit = mx.io.NDArrayIter(va_img, va_lbl, 50)
    mod = mx.mod.Module(lenet.get_symbol(10), context=mx.cpu())
    mod.fit(it, eval_metric="acc", optimizer="sgd",
            optimizer_params={"learning_rate": 0.05, "momentum": 0.9,
                              "wd": 1e-4},
            num_epoch=6,
            initializer=mx.initializer.Xavier(rnd_type="gaussian",
                                              factor_type="in",
                                              magnitude=2))
    vit.reset()
    acc = mod.score(vit, "acc")[0][1]
    assert acc >= 0.95, f"real-data LeNet val-acc gate failed: {acc}"
