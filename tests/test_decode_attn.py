"""Decode-attention Pallas kernel (ISSUE 19): flash-decode parity,
selection discipline, and the zero-compile serving contract.

The kernel replaces only the attention READ of ``attention_decode`` —
RoPE and the cache writes stay the shared XLA helpers — so the parity
gates here assert three things at once: outputs within the tier
tolerance, cache contents BIT-identical across tiers, and cursors
equal. Both cursor layouts (scalar single-session and per_slot pool),
both window sizes (S=1 steady state, S>1 chunked prefill), staggered
cursors including slot reuse, and the fp8 KV-cache storage tier all
run through the same harness. Selection rides the standard kernel-tier
rules: a scripted slower measurement can never pick the kernel, and
with the kernel + fp8 cache armed the decode engine compiles nothing
after warmup at any rung.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import kernel_tier, program_cache
from mxnet_tpu.base import MXNetError
from mxnet_tpu.ops.registry import get_op

OP = get_op("attention_decode")
B, H, DH, C = 2, 2, 8, 32


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.delenv("MXNET_KERNEL_TIER", raising=False)
    monkeypatch.delenv("MXNET_LM_CACHE_DTYPE", raising=False)
    kernel_tier.clear()
    yield
    kernel_tier.clear()


def _attrs(per_slot=False, cache_dtype="", rope=False, capacity=C):
    return OP.normalize_attrs({"capacity": capacity, "per_slot": per_slot,
                               "cache_dtype": cache_dtype, "rope": rope})


def _state(S=1, dtype="float32", per_slot=False, cursors=None,
           cache_dtype=None, seed=0, capacity=C):
    """Random q/k/v + a cache whose live prefix holds real rows."""
    rng = np.random.RandomState(seed)
    dt = np.dtype(dtype)
    q, k, v = (jnp.asarray(rng.randn(B, H, S, DH), dt) for _ in range(3))
    cdt = np.dtype(cache_dtype) if cache_dtype else dt
    k_cache = jnp.asarray(rng.randn(B, H, capacity, DH), cdt)
    v_cache = jnp.asarray(rng.randn(B, H, capacity, DH), cdt)
    if cursors is None:
        cursors = [3] * B if per_slot else 3
    cur = jnp.asarray(np.reshape(cursors, (B, 1)), jnp.int32) \
        if per_slot else jnp.asarray([cursors], jnp.int32)
    return [q, k, v], [k_cache, v_cache, cur]


def _both(attrs, inputs, aux):
    ref_o, ref_a = OP.forward(attrs, inputs, aux, False, None)
    pal_o, pal_a = OP.variants["pallas"]["fn"](attrs, inputs, aux,
                                               False, None)
    return ref_o[0], ref_a, pal_o[0], pal_a


def _assert_parity(attrs, inputs, aux, tol):
    ref, ref_aux, pal, pal_aux = _both(attrs, inputs, aux)
    assert ref.dtype == pal.dtype
    np.testing.assert_allclose(np.asarray(ref, np.float32),
                               np.asarray(pal, np.float32), atol=tol,
                               rtol=tol)
    # cache writes are the SHARED helper: bit-identical, dtype kept
    for r, p in zip(ref_aux[:2], pal_aux[:2]):
        assert r.dtype == p.dtype
        assert np.array_equal(np.asarray(r, np.float32),
                              np.asarray(p, np.float32))
    assert np.array_equal(np.asarray(ref_aux[2]), np.asarray(pal_aux[2]))


# ------------------------------------------------------------- parity
@pytest.mark.parametrize("dtype,tol", [("float32", 2e-4),
                                       ("bfloat16", 2e-2)])
@pytest.mark.parametrize("per_slot", [False, True])
@pytest.mark.parametrize("S", [1, 4])
def test_decode_kernel_parity(dtype, tol, per_slot, S):
    cursors = [1, 9] if per_slot else 5
    inputs, aux = _state(S=S, dtype=dtype, per_slot=per_slot,
                         cursors=cursors)
    _assert_parity(_attrs(per_slot=per_slot), inputs, aux, tol)


def test_decode_kernel_parity_rope():
    inputs, aux = _state(S=1, per_slot=True, cursors=[2, 7])
    _assert_parity(_attrs(per_slot=True, rope=True), inputs, aux, 2e-4)


def test_decode_kernel_parity_staggered_and_edge_cursors():
    """Slots at position 0, mid-stream, and at the last legal window
    start — the cursor-bounded HBM read must still cover exactly the
    live prefix of every row."""
    inputs, aux = _state(S=1, per_slot=True, cursors=[0, C - 1])
    _assert_parity(_attrs(per_slot=True), inputs, aux, 2e-4)


def test_decode_kernel_parity_slot_reuse():
    """Retire-and-rejoin: advance both slots, reset slot 0's cursor to
    0 (the pool's join path resets ONLY the cursor), decode again —
    the kernel's bounded read must mask the stale suffix exactly like
    the XLA composition's -inf mask."""
    attrs = _attrs(per_slot=True)
    inputs, aux = _state(S=1, per_slot=True, cursors=[4, 11])
    _, ref_aux, _, pal_aux = _both(attrs, inputs, aux)
    rng = np.random.RandomState(9)
    nxt = [jnp.asarray(rng.randn(B, H, 1, DH), jnp.float32)
           for _ in range(3)]
    rejoin = jnp.asarray([[0], [12]], jnp.int32)    # slot 0 reused
    _assert_parity(attrs, nxt, [ref_aux[0], ref_aux[1], rejoin], 2e-4)


def test_decode_kernel_fp8_cache():
    """The fp8 storage tier: cache cells stay float8_e4m3fn through the
    step (writes cast on store, reads dequantize), and the kernel
    matches the XLA composition reading the SAME fp8 cells."""
    inputs, aux = _state(S=1, per_slot=True, cursors=[2, 6],
                         cache_dtype="float8_e4m3fn")
    attrs = _attrs(per_slot=True, cache_dtype="fp8")
    ref, ref_aux, pal, pal_aux = _both(attrs, inputs, aux)
    assert ref_aux[0].dtype == np.dtype("float8_e4m3fn")
    assert pal_aux[0].dtype == np.dtype("float8_e4m3fn")
    np.testing.assert_allclose(np.asarray(ref), np.asarray(pal),
                               atol=2e-4, rtol=2e-4)
    assert np.array_equal(np.asarray(ref_aux[0], np.float32),
                          np.asarray(pal_aux[0], np.float32))


def test_pallas_variant_rejects_training():
    inputs, aux = _state()
    with pytest.raises(MXNetError, match="inference"):
        OP.variants["pallas"]["fn"](_attrs(), inputs, aux, True, None)


# -------------------------------------------------- eligibility + gate
def test_decode_eligibility_bounds():
    elig = OP.variants["pallas"]["eligible"]
    qs = (B, H, 1, DH)
    cs = (B, H, C, DH)
    shapes = [qs, qs, qs, cs, cs, (B, 1)]
    f32 = ["float32"] * 5 + ["int32"]
    assert elig(_attrs(), shapes, f32)
    # fp8 cache cells are in the gate set
    fp8 = ["float32"] * 3 + ["float8_e4m3fn"] * 2 + ["int32"]
    assert elig(_attrs(cache_dtype="fp8"), shapes, fp8)
    # bounds: window rows, head dim, q dtype
    big_s = [(B, H, 65, DH)] + shapes[1:]
    assert not elig(_attrs(), big_s, f32)
    wide = [(B, H, 1, 513)] * 3 + [(B, H, C, 513)] * 2 + [(B, 1)]
    assert not elig(_attrs(), wide, f32)
    assert not elig(_attrs(), shapes, ["int8"] + f32[1:])


def test_decode_numerics_gate():
    qs, cs = (B, H, 1, DH), (B, H, C, DH)
    ok, err = kernel_tier.numerics_gate(
        OP, _attrs(per_slot=True), [qs, qs, qs, cs, cs, (B, 1)],
        ["float32"] * 5 + ["int32"], is_train=False)
    assert ok, f"max_abs_err={err}"


def test_decode_pallas_never_selected_when_slower(monkeypatch):
    """The decode kernel rides the same scripted-timer autotune as every
    other variant: a slower measurement can never select it."""
    qs, cs = (B, H, 1, DH), (B, H, C, DH)
    shapes = [qs, qs, qs, cs, cs, (B, 1)]
    dtypes = ["float32"] * 5 + ["int32"]
    times = iter([1.0, 3.0])                   # xla 1ms, pallas 3ms
    monkeypatch.setattr(kernel_tier, "_backend", lambda: "tpu")
    monkeypatch.setattr(kernel_tier, "_device_kind", lambda: "TPU test")
    monkeypatch.setattr(kernel_tier, "_time_variant",
                        lambda run, r, x, reps: next(times) / 1e3)
    assert kernel_tier.resolve(OP, _attrs(per_slot=True), shapes,
                               dtypes, False) == "xla"
    assert "slower" in kernel_tier.decisions()[-1]["reason"]


# ------------------------------------------- serving: zero compiles
V, D, L, NH, CAP = 64, 32, 2, 4, 32


def _decoder_args():
    from mxnet_tpu.models import transformer as tfm
    np.random.seed(0)
    sym = tfm.get_symbol(vocab_size=V, d_model=D, n_layer=L, n_head=NH,
                         seq_len=8, include_loss=False, max_seq_len=CAP)
    mod = mx.mod.Module(sym, label_names=[])
    mod.bind([("data", (1, 8))], None, for_training=False)
    mod.init_params(mx.initializer.Xavier(rnd_type="gaussian",
                                          magnitude=2))
    args, _ = mod.get_params()
    return args


@pytest.mark.parametrize("cache_dtype", [None, "fp8"])
def test_decode_engine_zero_compiles_with_kernel_armed(monkeypatch,
                                                       cache_dtype):
    """The acceptance gate: MXNET_KERNEL_TIER=pallas (+ the fp8 cache
    tier) armed, compile_count() delta == 0 after warmup at EVERY
    ladder rung, with requests joining and retiring across rungs."""
    from mxnet_tpu.models import transformer as tfm
    monkeypatch.setenv("MXNET_KERNEL_TIER", "pallas")
    kernel_tier.clear()
    args = _decoder_args()
    dsym = tfm.get_decode_symbol(vocab_size=V, d_model=D, n_layer=L,
                                 n_head=NH, capacity=CAP, per_slot=True,
                                 max_seq_len=CAP,
                                 cache_dtype=cache_dtype)
    sched = mx.serve.serve_decoder(dsym, args, name=f"za{cache_dtype}",
                                   ladder=[1, 2, 4], start=True)
    try:
        rs = np.random.RandomState(0)
        # warmup pinned every rung at engine build; steady state now
        mark = program_cache.compile_count()
        handles = [sched.submit(rs.randint(0, V, 4).tolist(),
                                max_new_tokens=6) for _ in range(6)]
        outs = [h.result(timeout=600) for h in handles]
        assert all(len(o) == 6 for o in outs)
        assert program_cache.compile_count() - mark == 0
        assert sched.stats()["compiles_since_warmup"] == 0
    finally:
        sched.stop()


def test_decode_driver_kernel_vs_xla_logits(monkeypatch):
    """End to end through Module + KVCacheDecoder: the forced-kernel
    decode chain reproduces the default chain's logits step for step."""
    from mxnet_tpu.models import transformer as tfm
    args = _decoder_args()
    tokens = np.random.RandomState(3).randint(0, V, (2, 8))

    def _run():
        dsym = tfm.get_decode_symbol(vocab_size=V, d_model=D, n_layer=L,
                                     n_head=NH, capacity=CAP,
                                     max_seq_len=CAP)
        dec = mx.mod.Module(dsym, label_names=[])
        dec.bind([("data", (2, 1))], None, for_training=False)
        dec.init_params(initializer=None, arg_params=args,
                        aux_params={}, allow_missing=True)
        drv = tfm.KVCacheDecoder(dec, capacity=CAP)
        return [drv.step(tokens[:, t:t + 1]).asnumpy()
                for t in range(tokens.shape[1])]

    base = _run()
    monkeypatch.setenv("MXNET_KERNEL_TIER", "pallas")
    kernel_tier.clear()
    forced = _run()
    for a, b in zip(base, forced):
        np.testing.assert_allclose(a, b, atol=2e-4, rtol=2e-4)
        assert np.array_equal(a.argmax(-1), b.argmax(-1))
