#!/usr/bin/env python
"""DCGAN: adversarial training with two Modules sharing a data path.

reference config: example/gan/dcgan.py — generator (Deconvolution stack)
and discriminator (Convolution stack) as separate Modules; the
discriminator is bound with inputs_need_grad=True so its input gradient
drives the generator's backward. Real images are synthetic blobs in this
zero-egress environment.

    python examples/dcgan.py --num-epochs 2
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym


def make_generator(ngf=32, nc=3, code_dim=64):
    rand = sym.var("rand")
    g = sym.Deconvolution(rand, name="g1", kernel=(4, 4), num_filter=ngf * 4,
                          no_bias=True)
    g = sym.BatchNorm(g, name="gbn1", fix_gamma=False)
    g = sym.Activation(g, name="gact1", act_type="relu")
    g = sym.Deconvolution(g, name="g2", kernel=(4, 4), stride=(2, 2),
                          pad=(1, 1), num_filter=ngf * 2, no_bias=True)
    g = sym.BatchNorm(g, name="gbn2", fix_gamma=False)
    g = sym.Activation(g, name="gact2", act_type="relu")
    g = sym.Deconvolution(g, name="g3", kernel=(4, 4), stride=(2, 2),
                          pad=(1, 1), num_filter=ngf, no_bias=True)
    g = sym.BatchNorm(g, name="gbn3", fix_gamma=False)
    g = sym.Activation(g, name="gact3", act_type="relu")
    g = sym.Deconvolution(g, name="g4", kernel=(4, 4), stride=(2, 2),
                          pad=(1, 1), num_filter=nc, no_bias=True)
    return sym.Activation(g, name="gout", act_type="tanh")


def make_discriminator(ndf=32):
    data = sym.var("data")
    label = sym.var("label")
    d = sym.Convolution(data, name="d1", kernel=(4, 4), stride=(2, 2),
                        pad=(1, 1), num_filter=ndf, no_bias=True)
    d = sym.LeakyReLU(d, name="dact1", act_type="leaky", slope=0.2)
    d = sym.Convolution(d, name="d2", kernel=(4, 4), stride=(2, 2),
                        pad=(1, 1), num_filter=ndf * 2, no_bias=True)
    d = sym.BatchNorm(d, name="dbn2", fix_gamma=False)
    d = sym.LeakyReLU(d, name="dact2", act_type="leaky", slope=0.2)
    d = sym.Convolution(d, name="d3", kernel=(4, 4), stride=(2, 2),
                        pad=(1, 1), num_filter=ndf * 4, no_bias=True)
    d = sym.BatchNorm(d, name="dbn3", fix_gamma=False)
    d = sym.LeakyReLU(d, name="dact3", act_type="leaky", slope=0.2)
    d = sym.Convolution(d, name="d4", kernel=(4, 4), num_filter=1,
                        no_bias=True)
    d = sym.Flatten(d)
    return sym.LogisticRegressionOutput(d, label, name="dloss")


def real_batch(rng, batch_size):
    """Synthetic 'real' images: bright gaussian blob on dark ground."""
    yy, xx = np.mgrid[0:32, 0:32]
    imgs = np.empty((batch_size, 3, 32, 32), np.float32)
    for i in range(batch_size):
        cy, cx = rng.uniform(8, 24, size=2)
        blob = np.exp(-(((yy - cy) ** 2 + (xx - cx) ** 2) / 30.0))
        imgs[i] = np.stack([blob] * 3) * 2 - 1
    return imgs


def main():
    parser = argparse.ArgumentParser(description="dcgan")
    parser.add_argument("--num-epochs", type=int, default=2)
    parser.add_argument("--batches-per-epoch", type=int, default=30)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--code-dim", type=int, default=64)
    parser.add_argument("--lr", type=float, default=0.0002)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO, format="%(asctime)s %(message)s")

    batch, zdim = args.batch_size, args.code_dim
    rng = np.random.RandomState(0)

    modG = mx.mod.Module(make_generator(code_dim=zdim), data_names=("rand",),
                         label_names=None, context=mx.current_context())
    modG.bind(data_shapes=[("rand", (batch, zdim, 1, 1))])
    modG.init_params(mx.initializer.Normal(0.02))
    modG.init_optimizer(optimizer="adam",
                        optimizer_params={"learning_rate": args.lr,
                                          "beta1": 0.5})

    modD = mx.mod.Module(make_discriminator(), data_names=("data",),
                         label_names=("label",),
                         context=mx.current_context())
    modD.bind(data_shapes=[("data", (batch, 3, 32, 32))],
              label_shapes=[("label", (batch, 1))],
              inputs_need_grad=True)
    modD.init_params(mx.initializer.Normal(0.02))
    modD.init_optimizer(optimizer="adam",
                        optimizer_params={"learning_rate": args.lr,
                                          "beta1": 0.5})

    def as_batch(data, label=None):
        return mx.io.DataBatch([mx.nd.array(data)],
                               [mx.nd.array(label)] if label is not None
                               else [])

    ones = np.ones((batch, 1), np.float32)
    zeros = np.zeros((batch, 1), np.float32)
    metric_d = mx.metric.CustomMetric(
        lambda lab, pred: ((pred > 0.5) == (lab > 0.5)).mean(), name="dacc")

    for epoch in range(args.num_epochs):
        metric_d.reset()
        for it in range(args.batches_per_epoch):
            noise = rng.randn(batch, zdim, 1, 1).astype(np.float32)
            modG.forward(as_batch(noise), is_train=True)
            fake = modG.get_outputs()[0]

            # discriminator: fake pass (label 0), stash grads
            modD.forward(as_batch(fake.asnumpy(), zeros), is_train=True)
            modD.backward()
            stash = [g.asnumpy() if g is not None else None
                     for g in modD._exec_group.grad_arrays]
            metric_d.update([mx.nd.array(zeros)], modD.get_outputs())

            # real pass (label 1), accumulate and update once
            modD.forward(as_batch(real_batch(rng, batch), ones),
                         is_train=True)
            modD.backward()
            for g, s in zip(modD._exec_group.grad_arrays, stash):
                if g is not None and s is not None:
                    g._set(g.asjax() + s)
            modD.update()
            metric_d.update([mx.nd.array(ones)], modD.get_outputs())

            # generator: push fakes toward label 1 through D's input grad
            modD.forward(as_batch(fake.asnumpy(), ones), is_train=True)
            modD.backward()
            diff = modD.get_input_grads()[0]
            modG.backward([diff])
            modG.update()

        name, val = metric_d.get()
        logging.info("epoch %d  %s=%.3f", epoch, name, val)


if __name__ == "__main__":
    main()
