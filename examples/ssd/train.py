"""Train SSD on the synthetic-shapes detection task.

reference: example/ssd/train.py — same flow: det iterator with box-aware
augmenters -> multibox training symbol -> Module.fit with a composite
cls/loc metric, then decode detections with the inference symbol.

    python examples/ssd/train.py --epochs 8 --batch-size 16
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import mxnet_tpu as mx  # noqa: E402
from examples.ssd import data as shapes_data  # noqa: E402
from examples.ssd import symbol as ssd_symbol  # noqa: E402


class MultiBoxMetric(mx.metric.EvalMetric):
    """Composite cls-CE / loc-smoothL1 metric (reference:
    example/ssd/evaluate/eval_metric.py MultiBoxMetric)."""

    def __init__(self):
        super().__init__("MultiBox")
        self.num = 2
        self.reset()

    def reset(self):
        self.sum_metric = [0.0, 0.0]
        self.num_inst = [0, 0]

    def update(self, labels, preds):
        cls_prob = preds[0].asnumpy()        # (N, C+1, A)
        loc_loss = preds[1].asnumpy()
        cls_label = preds[2].asnumpy()       # (N, A)
        valid = cls_label >= 0
        idx = cls_label.astype(int)
        n, _, a = cls_prob.shape
        picked = cls_prob[np.arange(n)[:, None], idx, np.arange(a)[None, :]]
        ce = -np.log(np.maximum(picked, 1e-12)) * valid
        self.sum_metric[0] += ce.sum()
        self.num_inst[0] += int(valid.sum())
        self.sum_metric[1] += loc_loss.sum()
        self.num_inst[1] += max(int(valid.sum()), 1)

    def get(self):
        return (["cross_entropy", "smooth_l1"],
                [self.sum_metric[i] / max(self.num_inst[i], 1)
                 for i in range(2)])


def build_iters(args, rng=None):
    rng = rng or np.random.RandomState(42)
    imgs, labs = shapes_data.make_shapes_dataset(
        args.num_images, size=args.data_size, rng=rng)
    vimgs, vlabs = shapes_data.make_shapes_dataset(
        max(args.num_images // 4, args.batch_size), size=args.data_size,
        rng=rng)
    shape = (3, args.data_size, args.data_size)
    train_aug = mx.image.CreateDetAugmenter(
        shape, rand_crop=0.5, rand_pad=0.5, rand_mirror=True,
        mean=np.zeros(3), std=np.full(3, 255.0))
    val_aug = mx.image.CreateDetAugmenter(shape, mean=np.zeros(3),
                                          std=np.full(3, 255.0))
    train = mx.image.ImageDetIter(args.batch_size, shape, imgs, labs,
                                  shuffle=True, aug_list=train_aug,
                                  max_objects=3)
    val = mx.image.ImageDetIter(args.batch_size, shape, vimgs, vlabs,
                                aug_list=val_aug, max_objects=3)
    return train, val


def train(args):
    train_iter, val_iter = build_iters(args)
    net = ssd_symbol.get_train_symbol(num_classes=2, width=args.width)
    mod = mx.mod.Module(net, data_names=("data",), label_names=("label",),
                        context=mx.context.current_context())
    metric = MultiBoxMetric()
    mod.fit(train_iter, eval_data=val_iter, eval_metric=metric,
            num_epoch=args.epochs,
            initializer=mx.initializer.Xavier(),
            optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9,
                              "wd": 5e-4},
            batch_end_callback=mx.callback.Speedometer(args.batch_size,
                                                       args.log_every))
    return mod


def detect(mod, args, images):
    """Decode detections with the trained weights (reference:
    example/ssd/detect/detector.py)."""
    det_sym = ssd_symbol.get_detect_symbol(num_classes=2, width=args.width)
    shape = (len(images), 3, args.data_size, args.data_size)
    exe = det_sym.simple_bind(ctx=mx.context.current_context(),
                              grad_req="null", data=shape)
    arg_params, aux_params = mod.get_params()
    exe.copy_params_from(arg_params, aux_params, allow_extra_params=True)
    batch = np.stack([img.astype(np.float32).transpose(2, 0, 1) / 255.0
                      for img in images])
    exe.forward(is_train=False, data=batch)
    return exe.outputs[0].asnumpy()    # (N, A, 6)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=8)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--num-images", type=int, default=128)
    p.add_argument("--data-size", type=int, default=96)
    p.add_argument("--width", type=int, default=32)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--log-every", type=int, default=10)
    args = p.parse_args()
    import logging
    logging.basicConfig(level=logging.INFO)
    mod = train(args)
    imgs, labs = shapes_data.make_shapes_dataset(
        4, size=args.data_size, rng=np.random.RandomState(7))
    dets = detect(mod, args, imgs)
    for i, det in enumerate(dets):
        kept = det[det[:, 0] >= 0]
        best = kept[np.argsort(-kept[:, 1])][:3] if len(kept) else []
        print(f"image {i}: gt={labs[i][:, 0].astype(int).tolist()} "
              f"top detections:")
        for row in best:
            print(f"  cls={int(row[0])} score={row[1]:.2f} "
                  f"box=({row[2]:.2f},{row[3]:.2f},{row[4]:.2f},"
                  f"{row[5]:.2f})")


if __name__ == "__main__":
    main()
