"""SSD detection symbol (reference: example/ssd/symbol_factory.py +
symbol/symbol_builder.py — multi-scale heads over a conv body, driving the
MultiBoxPrior/Target/Detection op trio).

The body here is a compact conv net sized for the synthetic-shapes task
(the reference's VGG16-reduced fills the same role for VOC); the head
wiring — per-scale loc/cls convs, channel-last flatten, anchor concat,
target matching, SoftmaxOutput with valid-normalization + hard-negative
ignore, smooth-L1 MakeLoss — follows the reference construction.
"""
import mxnet_tpu as mx

# per-scale anchor config: (sizes, ratios) -> A = len(sizes)+len(ratios)-1
SCALES = [
    ((0.15, 0.25), (1.0, 2.0, 0.5)),
    ((0.4, 0.55), (1.0, 2.0, 0.5)),
    ((0.7, 0.85), (1.0, 2.0, 0.5)),
]


def _conv_block(data, num_filter, name, stride=(1, 1)):
    c = mx.sym.Convolution(data, kernel=(3, 3), pad=(1, 1), stride=stride,
                           num_filter=num_filter, name=f"{name}_conv")
    b = mx.sym.BatchNorm(c, fix_gamma=False, name=f"{name}_bn")
    return mx.sym.Activation(b, act_type="relu", name=f"{name}_relu")


def _body(data, width=32):
    """Three detection scales at /8, /16, /32."""
    x = _conv_block(data, width, "b1a")
    x = mx.sym.Pooling(x, kernel=(2, 2), stride=(2, 2), pool_type="max",
                       name="p1")
    x = _conv_block(x, width * 2, "b2a")
    x = mx.sym.Pooling(x, kernel=(2, 2), stride=(2, 2), pool_type="max",
                       name="p2")
    x = _conv_block(x, width * 2, "b3a")
    f1 = _conv_block(x, width * 2, "b3b")
    x = mx.sym.Pooling(f1, kernel=(2, 2), stride=(2, 2), pool_type="max",
                       name="p3")
    f2 = _conv_block(x, width * 4, "b4a")
    x = mx.sym.Pooling(f2, kernel=(2, 2), stride=(2, 2), pool_type="max",
                       name="p4")
    f3 = _conv_block(x, width * 4, "b5a")
    return [f1, f2, f3]


def multibox_layer(features, num_classes):
    """Per-scale heads -> (loc_preds, cls_preds, anchors), the exact
    contract the MultiBox ops expect (reference:
    symbol/common.py multibox_layer)."""
    loc_layers, cls_layers, anchor_layers = [], [], []
    for i, (feat, (sizes, ratios)) in enumerate(zip(features, SCALES)):
        num_anchors = len(sizes) + len(ratios) - 1
        loc = mx.sym.Convolution(feat, kernel=(3, 3), pad=(1, 1),
                                 num_filter=num_anchors * 4,
                                 name=f"loc_pred{i}_conv")
        loc = mx.sym.transpose(loc, axes=(0, 2, 3, 1))
        loc_layers.append(mx.sym.Flatten(loc))
        cls = mx.sym.Convolution(
            feat, kernel=(3, 3), pad=(1, 1),
            num_filter=num_anchors * (num_classes + 1),
            name=f"cls_pred{i}_conv")
        cls = mx.sym.transpose(cls, axes=(0, 2, 3, 1))
        cls = mx.sym.Reshape(cls, shape=(0, -1, num_classes + 1))
        cls_layers.append(cls)
        anchor_layers.append(mx.sym.MultiBoxPrior(
            feat, sizes=sizes, ratios=ratios, clip=True,
            name=f"anchors{i}"))
    loc_preds = mx.sym.Concat(*loc_layers, dim=1, num_args=len(loc_layers),
                              name="loc_preds")
    cls_concat = mx.sym.Concat(*cls_layers, dim=1,
                               num_args=len(cls_layers))
    cls_preds = mx.sym.transpose(cls_concat, axes=(0, 2, 1),
                                 name="cls_preds")   # (N, C+1, A)
    anchors = mx.sym.Concat(*anchor_layers, dim=1,
                            num_args=len(anchor_layers), name="anchors")
    return loc_preds, cls_preds, anchors


def get_train_symbol(num_classes=2, width=32):
    data = mx.sym.var("data")
    label = mx.sym.var("label")
    loc_preds, cls_preds, anchors = multibox_layer(_body(data, width),
                                                   num_classes)
    tmp = mx.sym.MultiBoxTarget(anchors, label, cls_preds,
                                overlap_threshold=0.5,
                                ignore_label=-1,
                                negative_mining_ratio=3,
                                name="multibox_target")
    loc_target, loc_mask, cls_target = tmp[0], tmp[1], tmp[2]
    cls_prob = mx.sym.SoftmaxOutput(cls_preds, cls_target,
                                    ignore_label=-1, use_ignore=True,
                                    multi_output=True,
                                    normalization="valid",
                                    name="cls_prob")
    loc_diff = loc_mask * (loc_preds - loc_target)
    loc_loss = mx.sym.MakeLoss(mx.sym.smooth_l1(loc_diff, scalar=1.0),
                               grad_scale=1.0, normalization="valid",
                               name="loc_loss")
    # stop-gradient views give metrics the matching targets
    cls_label = mx.sym.MakeLoss(mx.sym.BlockGrad(cls_target), grad_scale=0,
                                name="cls_label")
    return mx.sym.Group([cls_prob, loc_loss, cls_label])


def get_detect_symbol(num_classes=2, width=32, nms_threshold=0.45,
                      score_threshold=0.1):
    data = mx.sym.var("data")
    loc_preds, cls_preds, anchors = multibox_layer(_body(data, width),
                                                   num_classes)
    cls_prob = mx.sym.softmax(cls_preds, axis=1, name="cls_prob_det")
    return mx.sym.MultiBoxDetection(cls_prob, loc_preds, anchors,
                                    nms_threshold=nms_threshold,
                                    threshold=score_threshold,
                                    force_suppress=False, clip=True,
                                    name="detection")
