"""Synthetic-shapes detection dataset.

Stands in for VOC in the reference's example/ssd: images contain 1-3
colored objects — squares (class 0) and disks (class 1) — on a noisy
background, with normalized [cls, x1, y1, x2, y2] box labels. Convergence
on it proves the full SSD pipeline (augmenters -> anchors -> matching ->
losses -> NMS decode) end to end without external data.
"""
import numpy as np


def make_shapes_dataset(n_images, size=96, rng=None, max_objects=3):
    rng = rng or np.random.RandomState(0)
    images, labels = [], []
    for _ in range(n_images):
        img = rng.randint(0, 40, (size, size, 3)).astype(np.uint8)
        n_obj = rng.randint(1, max_objects + 1)
        rows = []
        for _ in range(n_obj):
            side = rng.randint(size // 5, size // 2)
            x0 = rng.randint(0, size - side)
            y0 = rng.randint(0, size - side)
            color = rng.randint(120, 255, 3)
            cls = rng.randint(0, 2)
            if cls == 0:                    # filled square
                img[y0:y0 + side, x0:x0 + side] = color
            else:                           # filled disk
                yy, xx = np.mgrid[0:size, 0:size]
                cy, cx = y0 + side / 2, x0 + side / 2
                mask = (yy - cy) ** 2 + (xx - cx) ** 2 <= (side / 2) ** 2
                img[mask] = color
            rows.append([cls, x0 / size, y0 / size,
                         (x0 + side) / size, (y0 + side) / size])
        images.append(img)
        labels.append(np.array(rows, dtype=np.float32))
    return images, labels
