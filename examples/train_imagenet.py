#!/usr/bin/env python
"""Train ResNet-50 (or friends) at ImageNet shapes — the flagship
throughput config.

reference config: example/image-classification/train_imagenet.py (the
BASELINE.json north-star row). Data is synthetic by default (zero-egress
environment); throughput numbers are identical either way since decode
happens off the measured path in NDArrayIter. Run:

    python examples/train_imagenet.py --network resnet --num-layers 50 \
        --batch-size 64 --num-epochs 1
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from mxnet_tpu.models import (resnet, alexnet, vgg, inception_bn,
                              inception_v3)
from common import data, fit


def main():
    parser = argparse.ArgumentParser(description="train imagenet")
    parser.add_argument("--network", type=str, default="resnet",
                        choices=("resnet", "alexnet", "vgg", "inception-bn",
                                 "inception-v3"))
    parser.add_argument("--num-layers", type=int, default=50)
    parser.add_argument("--num-classes", type=int, default=1000)
    parser.add_argument("--num-examples", type=int, default=2560)
    parser.add_argument("--num-val", type=int, default=256)
    parser.add_argument("--data-train", type=str, default=None,
                        help=".rec pack for real training data (routes "
                             "through ImageRecordIter: multiprocess "
                             "decode + augmentation)")
    parser.add_argument("--data-val", type=str, default=None)
    fit.add_fit_args(parser)
    parser.set_defaults(batch_size=64, num_epochs=1, lr=0.1,
                        disp_batches=10)
    args = parser.parse_args()

    if args.network == "resnet":
        net = resnet.get_symbol(num_classes=args.num_classes,
                                num_layers=args.num_layers,
                                image_shape="3,224,224")
    elif args.network == "alexnet":
        net = alexnet.get_symbol(num_classes=args.num_classes)
    elif args.network == "vgg":
        net = vgg.get_symbol(num_classes=args.num_classes,
                             num_layers=args.num_layers)
    elif args.network == "inception-v3":
        net = inception_v3.get_symbol(num_classes=args.num_classes)
    else:
        net = inception_bn.get_symbol(num_classes=args.num_classes)

    # inception-v3 is a 299x299 architecture (its global pool is 8x8)
    image_shape = (3, 299, 299) if args.network == "inception-v3" \
        else (3, 224, 224)
    if args.data_train:
        # real data: RecordIO -> multiprocess decode + train augmentation
        # (reference: train_imagenet.py's ImageRecordIter config)
        import mxnet_tpu as mx
        kw = dict(data_shape=image_shape, batch_size=args.batch_size,
                  mean_r=123.68, mean_g=116.779, mean_b=103.939)
        train = mx.image.ImageRecordIter(
            args.data_train, shuffle=True, rand_crop=True,
            rand_mirror=True, resize=image_shape[-1] + 32, **kw)
        # no --data-val -> no validation (never score on the train pack)
        val = mx.image.ImageRecordIter(
            args.data_val, resize=image_shape[-1] + 32, **kw) \
            if args.data_val else None
        iters = (train, val)
    else:
        iters = data.imagenet_like_iters(args.batch_size,
                                         num_classes=args.num_classes,
                                         image_shape=image_shape,
                                         num_train=args.num_examples,
                                         num_val=args.num_val)
    fit.fit(args, net, iters)


if __name__ == "__main__":
    main()
