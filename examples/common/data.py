"""Dataset helpers for the example scripts.

The reference examples download MNIST/CIFAR/ImageNet (reference:
example/image-classification/train_mnist.py:14-26). This environment has
no network egress, so each loader first looks for the real files on disk
and otherwise *generates* a structured synthetic stand-in with the same
shapes/protocol: class prototypes + noise, which real models learn the
same way (convergence gates stay meaningful — an untrained net scores
1/num_classes, a working training loop reaches >0.9).
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

import mxnet_tpu as mx


def _read_idx_images(path):
    with gzip.open(path, "rb") as f:
        _, n, rows, cols = struct.unpack(">IIII", f.read(16))
        return np.frombuffer(f.read(), dtype=np.uint8).reshape(n, rows, cols)


def _read_idx_labels(path):
    with gzip.open(path, "rb") as f:
        _, n = struct.unpack(">II", f.read(8))
        return np.frombuffer(f.read(), dtype=np.uint8)


def synthetic_classification(num, shape, num_classes, seed=0, noise=0.35):
    """Prototype-plus-noise images: class k = fixed random pattern k."""
    rng = np.random.RandomState(seed)
    protos = (rng.rand(num_classes, *shape) - 0.5).astype(np.float32)
    labels = rng.randint(0, num_classes, size=num)
    imgs = protos[labels] + noise * rng.randn(num, *shape).astype(np.float32)
    return imgs.astype(np.float32), labels.astype(np.float32)


def real_digits(size=28, seed=0, val_frac=0.2):
    """Real handwritten-digit data available offline: the UCI ML
    hand-written digits set vendored inside scikit-learn (1797 genuine
    8x8 grayscale scans, 10 classes). Resized to ``size`` so the MNIST
    model configs run unchanged. Returns (tr_img, tr_lbl, va_img,
    va_lbl) with a deterministic shuffled split, images NCHW in [0, 1].

    This is the real-data convergence target when actual MNIST idx
    files are absent (no network egress here): a broken BatchNorm or
    optimizer that still passes prototype-synthetic gates will fail on
    these (reference gate analog: tests/python/train/test_mlp.py:88-100).
    """
    from sklearn.datasets import load_digits
    import cv2
    d = load_digits()
    imgs = (d.images / 16.0).astype(np.float32)
    if size != 8:
        imgs = np.stack([cv2.resize(im, (size, size),
                                    interpolation=cv2.INTER_LINEAR)
                         for im in imgs])
    labels = d.target.astype(np.float32)
    rng = np.random.RandomState(seed)
    order = rng.permutation(len(imgs))
    imgs, labels = imgs[order][:, None], labels[order]
    n_val = int(len(imgs) * val_frac)
    return (imgs[n_val:], labels[n_val:], imgs[:n_val], labels[:n_val])


def real_photo_patches(patch=32, stride=16, split_col=420, gap=None,
                       seed=0):
    """Real RGB photographs at CIFAR patch scale, available offline:
    scikit-learn vendors two genuine 427x640 photos (china.jpg,
    flower.jpg). Cut into ``patch`` x ``patch`` tiles on a ``stride``
    grid, labeled by source photo — a 2-class natural-image texture/
    color task with real pixel statistics. The train/val split is
    SPATIAL (train = left columns, val = right columns, with a >=patch
    gap) so overlapping tiles never leak across the split; passing the
    gate requires generalizing to unseen regions of the scene.

    Returns (tr_img, tr_lbl, va_img, va_lbl): images uint8 HWC.
    """
    from sklearn.datasets import load_sample_images
    photos = load_sample_images().images
    if gap is None:
        gap = patch               # guarantees zero tile overlap by itself

    def cut(img, c0, c1):
        return [img[y:y + patch, x:x + patch]
                for y in range(0, img.shape[0] - patch + 1, stride)
                for x in range(c0, c1 - patch + 1, stride)]

    tr, trl, va, val = [], [], [], []
    for lbl, img in enumerate(photos):
        t = cut(img, 0, split_col)
        v = cut(img, split_col + gap, img.shape[1])
        tr += t
        trl += [lbl] * len(t)
        va += v
        val += [lbl] * len(v)
    rng = np.random.RandomState(seed)
    order = rng.permutation(len(tr))
    tr = np.stack(tr)[order]
    trl = np.asarray(trl, np.float32)[order]
    return tr, trl, np.stack(va), np.asarray(val, np.float32)


def mnist_iters(batch_size, data_dir="data", flat=False, seed=0,
                num_train=8000, num_val=2000):
    """(train_iter, val_iter) of 28x28 digits — real MNIST if the idx
    files exist under ``data_dir``; else the real scikit-learn digits
    scans (resized); synthetic only as a last resort."""
    files = ["train-images-idx3-ubyte.gz", "train-labels-idx1-ubyte.gz",
             "t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz"]
    paths = [os.path.join(data_dir, f) for f in files]
    if all(os.path.exists(p) for p in paths):
        tr_img = _read_idx_images(paths[0]).astype(np.float32) / 255
        tr_lbl = _read_idx_labels(paths[1]).astype(np.float32)
        va_img = _read_idx_images(paths[2]).astype(np.float32) / 255
        va_lbl = _read_idx_labels(paths[3]).astype(np.float32)
        tr_img = tr_img[:, None]
        va_img = va_img[:, None]
    else:
        try:
            tr_img, tr_lbl, va_img, va_lbl = real_digits(seed=seed)
        except ImportError:
            tr_img, tr_lbl = synthetic_classification(
                num_train, (1, 28, 28), 10, seed=seed)
            va_img, va_lbl = synthetic_classification(
                num_val, (1, 28, 28), 10, seed=seed)  # same prototypes
    if flat:
        tr_img = tr_img.reshape(len(tr_img), -1)
        va_img = va_img.reshape(len(va_img), -1)
    train = mx.io.NDArrayIter(tr_img, tr_lbl, batch_size, shuffle=True)
    val = mx.io.NDArrayIter(va_img, va_lbl, batch_size)
    return train, val


def cifar_like_iters(batch_size, num_classes=10, seed=0,
                     num_train=6000, num_val=1500):
    """32x32x3 image iterators (synthetic CIFAR-10 stand-in)."""
    tr_img, tr_lbl = synthetic_classification(
        num_train, (3, 32, 32), num_classes, seed=seed)
    va_img, va_lbl = synthetic_classification(
        num_val, (3, 32, 32), num_classes, seed=seed)
    train = mx.io.NDArrayIter(tr_img, tr_lbl, batch_size, shuffle=True)
    val = mx.io.NDArrayIter(va_img, va_lbl, batch_size)
    return train, val


def imagenet_like_iters(batch_size, num_classes=1000, image_shape=(3, 224, 224),
                        num_train=2560, num_val=256, seed=0):
    """224x224 iterators for throughput runs (synthetic ImageNet shapes)."""
    tr_img, tr_lbl = synthetic_classification(
        num_train, image_shape, num_classes, seed=seed)
    va_img, va_lbl = synthetic_classification(
        num_val, image_shape, num_classes, seed=seed)
    train = mx.io.NDArrayIter(tr_img, tr_lbl, batch_size, shuffle=True)
    val = mx.io.NDArrayIter(va_img, va_lbl, batch_size)
    return train, val


def synthetic_sentences(num=2000, vocab=128, max_len=30, seed=0):
    """Integer token sequences with a learnable next-token structure
    (each token ~ (3*prev + class) mod vocab), variable lengths."""
    rng = np.random.RandomState(seed)
    sents = []
    for _ in range(num):
        length = rng.randint(5, max_len)
        s = [int(rng.randint(1, vocab))]
        for _ in range(length - 1):
            s.append(int((3 * s[-1] + 1) % (vocab - 1)) + 1)
        sents.append(s)
    return sents
