"""Shared argparse + fit wiring for the example scripts.

API parity with reference example/image-classification/common/fit.py
(add_fit_args / fit): common hyperparameter flags, checkpoint resume via
--load-epoch, Speedometer logging, kvstore selection.
"""
from __future__ import annotations

import argparse
import logging
import os

import mxnet_tpu as mx


def add_fit_args(parser):
    parser.add_argument("--num-epochs", type=int, default=10)
    parser.add_argument("--lr", type=float, default=0.05)
    parser.add_argument("--lr-factor", type=float, default=0.1)
    parser.add_argument("--lr-step-epochs", type=str, default="")
    parser.add_argument("--optimizer", type=str, default="sgd")
    parser.add_argument("--mom", type=float, default=0.9)
    parser.add_argument("--wd", type=float, default=1e-4)
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--disp-batches", type=int, default=20)
    parser.add_argument("--kv-store", type=str, default="local")
    parser.add_argument("--model-prefix", type=str, default=None)
    parser.add_argument("--load-epoch", type=int, default=None)
    parser.add_argument("--num-devices", type=int, default=1,
                        help="data-parallel device count (virtual CPU "
                        "devices or TPU chips)")
    parser.add_argument("--dtype", type=str, default="float32")
    return parser


def _contexts(args):
    if args.num_devices <= 1:
        return [mx.current_context()]
    return [mx.Context(mx.current_context().device_type, i)
            for i in range(args.num_devices)]


def fit(args, network, data_iters, **fit_kwargs):
    """Bind + train ``network`` on (train, val) iterators per ``args``."""
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(message)s")
    train, val = data_iters

    arg_params = aux_params = None
    begin_epoch = 0
    if args.model_prefix and args.load_epoch is not None:
        _, arg_params, aux_params = mx.model.load_checkpoint(
            args.model_prefix, args.load_epoch)
        begin_epoch = args.load_epoch
        logging.info("resumed %s at epoch %d", args.model_prefix,
                     begin_epoch)

    lr_scheduler = None
    if args.lr_step_epochs:
        epoch_size = max(train.num_data // args.batch_size, 1) \
            if hasattr(train, "num_data") else 100
        steps = [epoch_size * int(e)
                 for e in args.lr_step_epochs.split(",") if e]
        lr_scheduler = mx.lr_scheduler.MultiFactorScheduler(
            steps, args.lr_factor)

    optimizer_params = {"learning_rate": args.lr, "wd": args.wd}
    if args.optimizer in ("sgd", "nag"):
        optimizer_params["momentum"] = args.mom
    if lr_scheduler is not None:
        optimizer_params["lr_scheduler"] = lr_scheduler

    checkpoint = mx.callback.do_checkpoint(args.model_prefix) \
        if args.model_prefix else None

    contexts = _contexts(args)
    # overlap input with compute: decode/augment runs ahead of the step
    # in a background thread with batches staged to the training device
    # (reference: PrefetcherIter always tops the C++ iterator stack,
    # iter_prefetcher.h:129). Iterators that already prefetch pass through.
    if isinstance(train, mx.io.PrefetchingIter):
        train.ensure_device(contexts[0])
    else:
        train = mx.io.PrefetchingIter(train, device=contexts[0])
    if val is not None:
        if isinstance(val, mx.io.PrefetchingIter):
            val.ensure_device(contexts[0])
        else:
            val = mx.io.PrefetchingIter(val, device=contexts[0])

    mod = mx.mod.Module(network, context=contexts)
    mod.fit(train,
            eval_data=val,
            eval_metric=["acc"],
            optimizer=args.optimizer,
            optimizer_params=optimizer_params,
            arg_params=arg_params,
            aux_params=aux_params,
            begin_epoch=begin_epoch,
            num_epoch=args.num_epochs,
            batch_end_callback=mx.callback.Speedometer(
                args.batch_size, args.disp_batches),
            epoch_end_callback=checkpoint,
            kvstore=args.kv_store,
            initializer=mx.initializer.Xavier(rnd_type="gaussian",
                                              factor_type="in",
                                              magnitude=2),
            **fit_kwargs)
    return mod
