#!/usr/bin/env python
"""Train an MLP or LeNet on (possibly synthetic) MNIST.

reference config: example/image-classification/train_mnist.py — the M1
exit criterion of SURVEY.md §7. Run:

    python examples/train_mnist.py --network mlp --num-epochs 5
    python examples/train_mnist.py --network lenet
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from mxnet_tpu.models import mlp, lenet
from common import data, fit


def main():
    parser = argparse.ArgumentParser(description="train mnist")
    parser.add_argument("--network", choices=("mlp", "lenet"), default="mlp")
    parser.add_argument("--data-dir", type=str, default="data")
    fit.add_fit_args(parser)
    parser.set_defaults(batch_size=64, num_epochs=5, lr=0.05)
    args = parser.parse_args()

    flat = args.network == "mlp"
    net = (mlp if flat else lenet).get_symbol(num_classes=10)
    iters = data.mnist_iters(args.batch_size, data_dir=args.data_dir,
                             flat=flat)
    fit.fit(args, net, iters)


if __name__ == "__main__":
    main()
