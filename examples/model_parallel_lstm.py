#!/usr/bin/env python
"""Model-parallel LSTM character language model.

reference config: example/model-parallel-lstm/lstm.py:48-112 — each
pipeline stage of an unrolled LSTM LM (embedding, every LSTM layer, the
decoder) is tagged with its own ``ctx_group`` and placed on a distinct
device, so a model too big for one device's memory trains by streaming
activations across the group boundaries. The reference pins groups to
GPUs through executor-level ctx assignment; here ``group2ctx`` maps the
groups onto mesh devices and the placement pass turns boundaries into
sharding constraints (mxnet_tpu/parallel/placement.py) — XLA inserts the
transfers.

Real text: the model trains on this repository's own documentation
(README.md + docs/) as a character-level corpus — no download needed.

    python examples/model_parallel_lstm.py --num-epochs 2
"""
import argparse
import glob
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_corpus(seq_len, batch_size, val_frac=0.1):
    """Char-level corpus from the repo's documentation (real text)."""
    text = ""
    for path in [os.path.join(ROOT, "README.md")] + sorted(
            glob.glob(os.path.join(ROOT, "docs", "*.md"))):
        with open(path, errors="ignore") as f:
            text += f.read() + "\n"
    chars = sorted(set(text))
    vocab = {ch: i for i, ch in enumerate(chars)}
    ids = np.asarray([vocab[ch] for ch in text], dtype=np.float32)
    # next-char prediction: x = ids[t:t+T], y = ids[t+1:t+T+1]
    n_seq = (len(ids) - 1) // seq_len
    x = ids[:n_seq * seq_len].reshape(n_seq, seq_len)
    y = ids[1:n_seq * seq_len + 1].reshape(n_seq, seq_len)
    n_val = max(batch_size, int(n_seq * val_frac) // batch_size * batch_size)
    return (x[:-n_val], y[:-n_val]), (x[-n_val:], y[-n_val:]), len(chars)


def build_symbol(vocab_size, num_layers, num_hidden, num_embed, seq_len):
    """Unrolled LSTM LM with one ctx_group per pipeline stage
    (reference: lstm_unroll's AttrScope(ctx_group=...) tagging)."""
    with mx.AttrScope(ctx_group="embed"):
        data = sym.var("data")
        net = sym.Embedding(data, input_dim=vocab_size,
                            output_dim=num_embed, name="embed")
    for i in range(num_layers):
        with mx.AttrScope(ctx_group=f"layer{i}"):
            cell = mx.rnn.LSTMCell(num_hidden=num_hidden, prefix=f"l{i}_")
            net, _ = cell.unroll(seq_len, inputs=net, layout="NTC",
                                 merge_outputs=True)
    with mx.AttrScope(ctx_group="decode"):
        label = sym.var("softmax_label")
        flat = sym.Reshape(net, shape=(-1, num_hidden))
        fc = sym.FullyConnected(flat, num_hidden=vocab_size, name="cls")
        flat_label = sym.Reshape(label, shape=(-1,))
        return sym.SoftmaxOutput(fc, label=flat_label, name="softmax")


def main():
    parser = argparse.ArgumentParser(description="model-parallel LSTM LM")
    parser.add_argument("--num-layers", type=int, default=2)
    parser.add_argument("--num-hidden", type=int, default=64)
    parser.add_argument("--num-embed", type=int, default=32)
    parser.add_argument("--seq-len", type=int, default=32)
    parser.add_argument("--batch-size", type=int, default=16)
    parser.add_argument("--num-epochs", type=int, default=2)
    parser.add_argument("--lr", type=float, default=0.02)
    parser.add_argument("--max-batches", type=int, default=0,
                        help="cap batches/epoch (0 = full epoch)")
    args = parser.parse_args()

    (tx, ty), (vx, vy), vocab_size = load_corpus(args.seq_len,
                                                 args.batch_size)
    print(f"corpus: {len(tx)} train / {len(vx)} val sequences, "
          f"vocab {vocab_size}")

    net = build_symbol(vocab_size, args.num_layers, args.num_hidden,
                       args.num_embed, args.seq_len)

    # one device per pipeline stage, cycling over what the host has —
    # the reference's lstm.py maps layers to GPUs the same way
    from mxnet_tpu.context import _local_cpu_devices
    devs = [mx.cpu(i) for i in range(len(_local_cpu_devices()))]
    groups = ["embed"] + [f"layer{i}" for i in range(args.num_layers)] \
        + ["decode"]
    group2ctx = {g: devs[i % len(devs)] for i, g in enumerate(groups)}
    print("placement:", {g: str(c) for g, c in group2ctx.items()})

    grad_req = {name: "null" if name in ("data", "softmax_label")
                else "write" for name in net.list_arguments()}
    exe = net.simple_bind(devs[0], grad_req=grad_req, group2ctx=group2ctx,
                          data=(args.batch_size, args.seq_len),
                          softmax_label=(args.batch_size, args.seq_len))
    init = mx.initializer.Xavier()
    for name, arr in exe.arg_dict.items():
        if name not in ("data", "softmax_label"):
            init(name, arr)

    n_train = len(tx) // args.batch_size
    if args.max_batches:
        n_train = min(n_train, args.max_batches)

    def run_epoch(train):
        xs, ys = (tx, ty) if train else (vx, vy)
        n = n_train if train else len(xs) // args.batch_size
        tot_nll, tot_tok = 0.0, 0
        for b in range(n):
            lo = b * args.batch_size
            exe.arg_dict["data"][:] = xs[lo:lo + args.batch_size]
            exe.arg_dict["softmax_label"][:] = ys[lo:lo + args.batch_size]
            probs = exe.forward(is_train=train)[0].asnumpy()
            lab = ys[lo:lo + args.batch_size].reshape(-1).astype(int)
            tot_nll -= np.sum(np.log(np.maximum(
                probs[np.arange(lab.size), lab], 1e-10)))
            tot_tok += lab.size
            if train:
                exe.backward()
                for name, grad in exe.grad_dict.items():
                    if grad is None:
                        continue
                    w = exe.arg_dict[name]
                    w._set(w.asjax() - args.lr * grad.asjax())
        return float(np.exp(tot_nll / tot_tok))

    val_ppl = run_epoch(False)
    print(f"initial val perplexity {val_ppl:.1f} (uniform ~{vocab_size})")
    for epoch in range(args.num_epochs):
        train_ppl = run_epoch(True)
        val_ppl = run_epoch(False)
        print(f"epoch {epoch}: train ppl {train_ppl:.1f}, "
              f"val ppl {val_ppl:.1f}")
    if val_ppl >= vocab_size * 0.8:
        raise SystemExit(f"model failed to learn: val ppl {val_ppl:.1f}")
    print("MODEL_PARALLEL_LSTM_OK")


if __name__ == "__main__":
    main()
