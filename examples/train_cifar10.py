#!/usr/bin/env python
"""Train a small ResNet on CIFAR-10-shaped data.

reference config: example/image-classification/train_cifar10.py. Run:

    python examples/train_cifar10.py --num-layers 20 --num-epochs 10
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from mxnet_tpu.models import resnet
from common import data, fit


def main():
    parser = argparse.ArgumentParser(description="train cifar10")
    parser.add_argument("--num-layers", type=int, default=20)
    parser.add_argument("--num-classes", type=int, default=10)
    fit.add_fit_args(parser)
    parser.set_defaults(batch_size=128, num_epochs=10, lr=0.05,
                        lr_step_epochs="60,100")
    args = parser.parse_args()

    net = resnet.get_symbol(num_classes=args.num_classes,
                            num_layers=args.num_layers,
                            image_shape="3,32,32")
    iters = data.cifar_like_iters(args.batch_size,
                                  num_classes=args.num_classes)
    fit.fit(args, net, iters)


if __name__ == "__main__":
    main()
