#!/usr/bin/env python
"""Sort sequences with a bidirectional LSTM.

reference config: example/bi-lstm-sort/ — the classic demonstration that
a BiLSTM can emit, at every position, the element that belongs there in
the sorted order (each output sees the whole sequence through the
forward+backward passes). Data is synthetic: random digit strings,
labels are the same strings sorted.

    python examples/bi_lstm_sort.py --num-epochs 4
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import mxnet_tpu as mx  # noqa: E402


def make_batches(n, seq_len, vocab, batch_size, seed=0):
    rng = np.random.RandomState(seed)
    data = rng.randint(0, vocab, (n, seq_len)).astype(np.float32)
    label = np.sort(data, axis=1)
    return mx.io.NDArrayIter(data, label, batch_size=batch_size,
                             shuffle=True, label_name="softmax_label")


def build_symbol(seq_len, vocab, num_hidden, num_embed):
    data = mx.sym.var("data")
    label = mx.sym.var("softmax_label")
    embed = mx.sym.Embedding(data, input_dim=vocab, output_dim=num_embed,
                             name="embed")
    bi = mx.rnn.BidirectionalCell(
        mx.rnn.LSTMCell(num_hidden=num_hidden, prefix="fwd_"),
        mx.rnn.LSTMCell(num_hidden=num_hidden, prefix="bwd_"))
    outputs, _ = bi.unroll(seq_len, inputs=embed, layout="NTC",
                           merge_outputs=True)      # (N, T, 2H)
    pred = mx.sym.Reshape(outputs, shape=(-1, 2 * num_hidden))
    pred = mx.sym.FullyConnected(pred, num_hidden=vocab, name="cls")
    label_flat = mx.sym.Reshape(label, shape=(-1,))
    return mx.sym.SoftmaxOutput(pred, label_flat, name="softmax")


def main():
    p = argparse.ArgumentParser(description="bi-lstm sort")
    p.add_argument("--seq-len", type=int, default=10)
    p.add_argument("--vocab", type=int, default=10)
    p.add_argument("--num-hidden", type=int, default=64)
    p.add_argument("--num-embed", type=int, default=32)
    p.add_argument("--num-epochs", type=int, default=4)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--num-train", type=int, default=2000)
    p.add_argument("--lr", type=float, default=0.01)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    train = make_batches(args.num_train, args.seq_len, args.vocab,
                         args.batch_size)
    val = make_batches(max(args.num_train // 5, args.batch_size),
                       args.seq_len, args.vocab, args.batch_size, seed=7)
    net = build_symbol(args.seq_len, args.vocab, args.num_hidden,
                       args.num_embed)
    mod = mx.mod.Module(net, context=mx.context.current_context(),
                        label_names=("softmax_label",))
    mod.fit(train, eval_data=val, num_epoch=args.num_epochs,
            initializer=mx.initializer.Xavier(),
            optimizer="adam", optimizer_params={"learning_rate": args.lr},
            eval_metric="acc",
            batch_end_callback=mx.callback.Speedometer(args.batch_size,
                                                       20))
    acc = mod.score(val, "acc")[0][1]
    print(f"final per-token sort accuracy: {acc:.3f}")
    return acc


if __name__ == "__main__":
    main()
