#!/usr/bin/env python
"""Bucketed LSTM language model.

reference config: example/rnn/lstm_bucketing.py — BucketingModule +
BucketSentenceIter + stacked LSTM cells, perplexity metric. Uses PTB
text if ``--data-dir`` has ptb.train.txt, else a synthetic corpus.

    python examples/lstm_bucketing.py --num-epochs 3
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym
from common import data as data_mod


def tokenize_text(fname, vocab=None, invalid_label=-1, start_label=0):
    with open(fname) as f:
        lines = [line.split() for line in f]
    return mx.rnn.io.encode_sentences(lines, vocab=vocab,
                                      invalid_label=invalid_label,
                                      start_label=start_label)


def main():
    parser = argparse.ArgumentParser(description="bucketed LSTM LM")
    parser.add_argument("--data-dir", type=str, default="data")
    parser.add_argument("--num-layers", type=int, default=2)
    parser.add_argument("--num-hidden", type=int, default=200)
    parser.add_argument("--num-embed", type=int, default=200)
    parser.add_argument("--num-epochs", type=int, default=3)
    parser.add_argument("--lr", type=float, default=0.01)
    parser.add_argument("--mom", type=float, default=0.0)
    parser.add_argument("--wd", type=float, default=1e-5)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--disp-batches", type=int, default=20)
    parser.add_argument("--kv-store", type=str, default="local")
    args = parser.parse_args()

    buckets = [10, 20, 30, 40]
    start_label = 1
    invalid_label = 0

    ptb = os.path.join(args.data_dir, "ptb.train.txt")
    if os.path.exists(ptb):
        sentences, vocab = tokenize_text(ptb, start_label=start_label,
                                         invalid_label=invalid_label)
        val_sent, _ = tokenize_text(
            os.path.join(args.data_dir, "ptb.valid.txt"), vocab=vocab,
            invalid_label=invalid_label)
        vocab_size = len(vocab) + start_label
    else:
        vocab_size = 128
        sentences = data_mod.synthetic_sentences(2000, vocab=vocab_size,
                                                 max_len=max(buckets))
        val_sent = data_mod.synthetic_sentences(400, vocab=vocab_size,
                                                max_len=max(buckets), seed=7)

    data_train = mx.rnn.BucketSentenceIter(sentences, args.batch_size,
                                           buckets=buckets,
                                           invalid_label=invalid_label)
    data_val = mx.rnn.BucketSentenceIter(val_sent, args.batch_size,
                                         buckets=buckets,
                                         invalid_label=invalid_label)

    stack = mx.rnn.SequentialRNNCell()
    for i in range(args.num_layers):
        stack.add(mx.rnn.LSTMCell(num_hidden=args.num_hidden,
                                  prefix=f"lstm_l{i}_"))

    def sym_gen(seq_len):
        data = sym.var("data")
        label = sym.var("softmax_label")
        embed = sym.Embedding(data, input_dim=vocab_size,
                              output_dim=args.num_embed, name="embed")
        stack.reset()
        outputs, _ = stack.unroll(seq_len, inputs=embed, merge_outputs=True)
        pred = sym.Reshape(outputs, shape=(-1, args.num_hidden))
        pred = sym.FullyConnected(pred, num_hidden=vocab_size, name="pred")
        label_flat = sym.Reshape(label, shape=(-1,))
        out = sym.SoftmaxOutput(pred, label_flat, name="softmax")
        return out, ("data",), ("softmax_label",)

    model = mx.mod.BucketingModule(
        sym_gen=sym_gen,
        default_bucket_key=data_train.default_bucket_key,
        context=mx.current_context())

    import logging
    logging.basicConfig(level=logging.INFO, format="%(asctime)s %(message)s")
    model.fit(
        train_data=data_train,
        eval_data=data_val,
        eval_metric=mx.metric.Perplexity(invalid_label),
        optimizer="sgd",
        optimizer_params={"learning_rate": args.lr, "momentum": args.mom,
                          "wd": args.wd},
        initializer=mx.initializer.Xavier(factor_type="in", magnitude=2.34),
        num_epoch=args.num_epochs,
        batch_end_callback=mx.callback.Speedometer(args.batch_size,
                                                   args.disp_batches))


if __name__ == "__main__":
    main()
