#!/usr/bin/env python
"""Perf-regression watchdog over bench payloads + benchmark results.

The perf trajectory is product surface the same way correctness is —
and it has already been lost silently once (r05: the flagship number
vanished to a dead tunnel and nothing failed). This tool makes a
perf-shaped regression fail CI the way a lint rule does:

* **bench history** (``BENCH_r*.json``, driver format ``{"parsed":
  {...}}`` or a raw bench.py payload / stdout tail): per metric
  *series*, the newest run carrying the series is compared against the
  best prior run, with a tolerance wide enough for the documented
  session dispersion (BENCH_r04's env_note: back-to-back identical
  runs measured 0.956 and 1.137 — default 25%). Series are keyed by
  the payload's ``metric`` name, so a methodology change (r02 -> r03
  renamed the flagship) starts a fresh series instead of flagging a
  fake collapse. Variant rows (serve req/s, int8 speedup, lm tokens/s,
  ckpt stall ratio, ...) are series of their own.
* **results gates** (``benchmarks/results/*.json``): files that carry
  their own acceptance gates — boolean ``gate_*``/``*_pass`` flags and
  ``gate_pct`` thresholds over ``*_overhead_pct`` measurements — are
  re-checked, so a stale-but-failing recorded result cannot sit green.
* **fleet reports** (``--fleet``, repeatable): ``tools/fleetstat.py
  --json`` documents appended as runs of their own — the fleet-health
  series (``step.wall.p99_over_p50``, the cross-rank straggler
  spread) is tracked like any bench series, so a widening p99/p50 gap
  across sessions regresses CI the same way a throughput drop does.

Exit codes: 0 = no regressions, 1 = regressions/gate failures (each
listed on stdout), 2 = unusable input. ``--check`` runs the repo
defaults — the in-process tier-1 gate next to ``mxlint --check``.

Usage::

    python tools/perfwatch.py --check
    python tools/perfwatch.py --check --payload new_bench_stdout.json
    python tools/perfwatch.py --check --fleet fleet_r01.json --fleet fleet_r02.json
    python tools/perfwatch.py --history /path/to/BENCH_dir --tolerance 0.1
    python tools/perfwatch.py --json --check

Pure stdlib — runs anywhere the repo checks out.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DEFAULT_TOLERANCE = 0.25      # flagship session dispersion (BENCH_r04)

# payload sub-metrics tracked as their own series: (path, direction)
# direction "up" = bigger is better, "down" = smaller is better
VARIANT_PATHS = [
    (("serve", "req_per_sec"), "up"),
    (("serve", "latency_ms", "p99"), "down"),
    (("quant", "int8_speedup"), "up"),
    (("lm", "train_tokens_per_sec"), "up"),
    (("lm", "decode_tokens_per_sec"), "up"),
    (("lm", "max_context"), "up"),
    (("decode_batch", "slots1_tokens_per_sec"), "up"),
    (("decode_batch", "slots8_tokens_per_sec"), "up"),
    (("decode_batch", "speedup_8v1"), "up"),
    (("decode_batch", "ttft_2048_ms"), "down"),
    (("decode_batch", "spec_speedup"), "up"),
    (("decode_batch", "prefix_hit_rate"), "up"),
    (("spmd", "spmd_vs_kvstore"), "up"),
    (("ckpt", "exposed_ratio"), "down"),
    (("lm_mfu", "train_mfu_pct"), "up"),
    (("lm_mfu", "decode_fp8_tokens_per_sec"), "up"),
    (("lm_mfu", "decode_attn_speedup"), "up"),
]

# per-series tolerance overrides (substring match on the series name);
# CPU-fallback variant rows ride shared CI machines and are noisier
TOLERANCES = {
    "_cpu_fallback": 0.5,
}

_ROUND_RE = re.compile(r"r(\d+)")

# fleetstat --json series and whether bigger is better; anything the
# report grows later defaults to "down" (fleet-health series are
# spread/imbalance shaped: smaller is healthier)
FLEET_SERIES_DIRECTIONS = {
    "step.wall.p99_over_p50": "down",
    # worst-rank training-health state (0 ok / 1 degraded / 2 diverged)
    # from the health plane via fleetstat --json
    "train.health.state.max": "down",
}


# --------------------------------------------------------------- loading
def load_payload(path):
    """A bench payload dict from any of the shapes the driver leaves:
    the ``{"parsed": {...}}`` BENCH_r record, a raw payload object, or
    text whose last JSON line is the payload. None when unusable."""
    try:
        with open(path) as f:
            text = f.read()
    except OSError:
        return None
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        doc = None
        for line in reversed(text.strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    doc = json.loads(line)
                    break
                except json.JSONDecodeError:
                    continue
    if not isinstance(doc, dict):
        return None
    if isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    return doc if "metric" in doc else None


def _round_of(path):
    m = _ROUND_RE.findall(os.path.basename(path))
    return int(m[-1]) if m else None


def extract_series(payload):
    """{series_name: (value, direction)} for one payload's tracked
    metrics. Null / missing / error'd rows are skipped — an absent
    measurement is a coverage gap, not a regression."""
    out = {}
    metric = str(payload.get("metric", "?"))
    v = payload.get("value")
    if isinstance(v, (int, float)):
        out[metric] = (float(v), "up")
    for path, direction in VARIANT_PATHS:
        node = payload
        for key in path:
            node = node.get(key) if isinstance(node, dict) else None
            if node is None:
                break
        if isinstance(node, bool) or not isinstance(node, (int, float)):
            continue
        out[f"{metric}.{'.'.join(path)}"] = (float(node), direction)
    return out


def load_history(history_dir=None, extra_payloads=()):
    """Ordered [(tag, {series: (value, dir)})] — BENCH_r*.json rounds
    ascending, then any explicitly passed payloads (newest last)."""
    runs = []
    d = history_dir or REPO
    paths = sorted(glob.glob(os.path.join(d, "BENCH_r*.json")),
                   key=lambda p: (_round_of(p) or 0, p))
    for p in paths:
        payload = load_payload(p)
        if payload is not None:
            runs.append((os.path.basename(p), extract_series(payload)))
    for p in extra_payloads:
        payload = load_payload(p)
        if payload is None:
            raise ValueError(f"--payload {p}: not a bench payload")
        runs.append((os.path.basename(p), extract_series(payload)))
    return runs


def load_fleet_reports(paths):
    """[(tag, {series: (value, dir)})] from fleetstat --json reports —
    one run per report, series prefixed ``fleet.`` so they never
    collide with bench metric names."""
    runs = []
    for p in paths:
        try:
            with open(p) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            raise ValueError(f"--fleet {p}: not a fleetstat --json "
                             "report")
        series = doc.get("series") if isinstance(doc, dict) else None
        if not isinstance(series, dict):
            raise ValueError(f"--fleet {p}: no series block (produce "
                             "it with tools/fleetstat.py --json)")
        out = {}
        for name in sorted(series):
            val = series[name]
            if isinstance(val, bool) or not isinstance(val, (int, float)):
                continue
            direction = FLEET_SERIES_DIRECTIONS.get(name, "down")
            out[f"fleet.{name}"] = (float(val), direction)
        runs.append((os.path.basename(p), out))
    return runs


# ------------------------------------------------------------ comparison
def _tolerance_for(series, default):
    for sub, tol in TOLERANCES.items():
        if sub in series:
            return max(tol, default)
    return default


def compare_history(runs, tolerance=DEFAULT_TOLERANCE):
    """Regressions: for every series, the newest run carrying it vs the
    best earlier run carrying it. First samples pass vacuously."""
    regressions = []
    series_names = {}
    for _tag, series in runs:
        series_names.update({k: None for k in series})
    for name in series_names:
        samples = [(tag, series[name][0], series[name][1])
                   for tag, series in runs if name in series]
        if len(samples) < 2:
            continue
        tag, current, direction = samples[-1]
        prior = samples[:-1]
        if direction == "up":
            best_tag, best = max(((t, v) for t, v, _ in prior),
                                 key=lambda x: x[1])
        else:
            best_tag, best = min(((t, v) for t, v, _ in prior),
                                 key=lambda x: x[1])
        tol = _tolerance_for(name, tolerance)
        bad = (current < best * (1.0 - tol) if direction == "up"
               else current > best * (1.0 + tol))
        if bad:
            regressions.append({
                "kind": "history", "series": name, "current": current,
                "current_run": tag, "best": best, "best_run": best_tag,
                "direction": direction, "tolerance": tol})
    return regressions


# ---------------------------------------------------------- result gates
_GATED_PCT_KEY = re.compile(
    r"(analytic_overhead_pct|warm_overhead_pct)$")


def check_result_gates(results_dir=None):
    """Re-check the acceptance gates recorded inside
    benchmarks/results/*.json: boolean ``gate_*``/``*_pass`` flags must
    be truthy, and every ``*analytic_overhead_pct`` /
    ``warm_overhead_pct`` must sit under its dict's ``gate_pct``."""
    failures = []
    d = results_dir if results_dir is not None else \
        os.path.join(REPO, "benchmarks", "results")

    def walk(node, fname, where):
        if not isinstance(node, dict):
            return
        gate_pct = node.get("gate_pct")
        for key, val in node.items():
            here = f"{where}.{key}" if where else key
            if isinstance(val, dict):
                walk(val, fname, here)
                continue
            if isinstance(val, bool) and \
                    (key.startswith("gate_") or key.endswith("_pass")):
                if not val:
                    failures.append({"kind": "gate", "file": fname,
                                     "key": here, "value": val,
                                     "reason": "recorded gate is false"})
            elif isinstance(gate_pct, (int, float)) and \
                    isinstance(val, (int, float)) and \
                    _GATED_PCT_KEY.search(key):
                if val >= gate_pct:
                    failures.append({
                        "kind": "gate", "file": fname, "key": here,
                        "value": val, "gate_pct": gate_pct,
                        "reason": f"{val:.3f}% >= {gate_pct}% gate"})

    for path in sorted(glob.glob(os.path.join(d, "*.json"))):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            failures.append({"kind": "gate", "file": path, "key": "",
                             "value": None, "reason": "unreadable"})
            continue
        walk(doc if isinstance(doc, dict) else {},
             os.path.basename(path), "")
    return failures


# ------------------------------------------------------------------ main
def run(history_dir=None, results_dir=None, payloads=(),
        tolerance=DEFAULT_TOLERANCE, check_gates=True,
        fleet_reports=()):
    """The whole watchdog pass; returns (regressions, n_series, n_runs)."""
    runs = load_history(history_dir, payloads)
    runs += load_fleet_reports(fleet_reports)
    regressions = compare_history(runs, tolerance)
    if check_gates:
        regressions += check_result_gates(results_dir)
    n_series = len({name for _t, s in runs for name in s})
    return regressions, n_series, len(runs)


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Fail on perf regressions across bench history and "
                    "recorded benchmark gates.")
    p.add_argument("--check", action="store_true",
                   help="run the repo-default watchdog pass (the CI "
                        "gate; implied when no other input is given)")
    p.add_argument("--payload", action="append", default=[],
                   metavar="FILE",
                   help="bench payload(s) to append as the newest "
                        "run(s) — a bench.py stdout capture works")
    p.add_argument("--fleet", action="append", default=[],
                   metavar="FILE",
                   help="fleetstat --json report(s) to append as runs "
                        "— tracks the fleet-health series "
                        "(step.wall.p99_over_p50) across sessions")
    p.add_argument("--history", default=None, metavar="DIR",
                   help="directory holding BENCH_r*.json "
                        "(default: the repo root)")
    p.add_argument("--results", default=None, metavar="DIR",
                   help="benchmarks/results dir for the recorded-gate "
                        "re-check (default: the repo's)")
    p.add_argument("--no-gates", action="store_true",
                   help="skip the benchmarks/results gate re-check")
    p.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                   help="relative regression tolerance "
                        f"(default {DEFAULT_TOLERANCE})")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable report on stdout")
    args = p.parse_args(argv)

    try:
        regressions, n_series, n_runs = run(
            history_dir=args.history, results_dir=args.results,
            payloads=args.payload, tolerance=args.tolerance,
            check_gates=not args.no_gates, fleet_reports=args.fleet)
    except ValueError as exc:
        print(f"perfwatch: {exc}", file=sys.stderr)
        return 2
    if n_runs == 0:
        print("perfwatch: no bench history found", file=sys.stderr)
        return 2

    if args.as_json:
        print(json.dumps({"runs": n_runs, "series": n_series,
                          "regressions": regressions}, indent=2))
    else:
        for r in regressions:
            if r["kind"] == "history":
                arrow = "below best" if r["direction"] == "up" \
                    else "above best"
                print(f"REGRESSION {r['series']}: {r['current']:g} "
                      f"({r['current_run']}) {arrow} {r['best']:g} "
                      f"({r['best_run']}) beyond "
                      f"{r['tolerance'] * 100:.0f}% tolerance")
            else:
                print(f"GATE FAIL {r['file']}: {r['key']} — "
                      f"{r['reason']}")
        status = "FAIL" if regressions else "OK"
        print(f"perfwatch {status}: {n_series} series over {n_runs} "
              f"runs, {len(regressions)} regression(s)")
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
