#!/usr/bin/env python
"""Local cluster launcher for distributed training.

Reference counterpart: tools/launch.py (dmlc-tracker: ssh/mpi/sge/yarn
backends starting scheduler + N workers + S servers). The TPU-native
runtime has no servers and no scheduler process — workers are symmetric
collective peers coordinated by the jax.distributed service hosted on
worker 0 — so this launcher covers the `local` backend: spawn N worker
processes on this host with the DMLC_* env contract the framework's
``mxnet_tpu.kvstore.init_distributed`` consumes:

    DMLC_NUM_WORKER   total workers
    DMLC_WORKER_ID    this worker's rank
    DMLC_PS_ROOT_URI  coordinator host (worker 0)
    DMLC_PS_ROOT_PORT coordinator port

Multi-host launches belong to the cluster scheduler (GKE/slurm/xpk set
the same variables per host); `-s` is accepted for command-line parity
with the reference and ignored with a note.

Usage (matches reference tests/nightly/test_all.sh:36):
    python tools/launch.py -n 4 python my_training_script.py
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time


def _free_port():
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="launch N local distributed workers")
    parser.add_argument("-n", "--num-workers", type=int, required=True,
                        help="number of worker processes")
    parser.add_argument("-s", "--num-servers", type=int, default=0,
                        help="ignored: the all-reduce design has no "
                             "server processes (reference parity flag)")
    parser.add_argument("--launcher", default="local",
                        choices=["local"],
                        help="only 'local' is supported; multi-host "
                             "launches come from the cluster scheduler")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="worker command line")
    args = parser.parse_args(argv)
    if not args.command:
        parser.error("no worker command given")
    if args.num_servers:
        print("launch.py: note: -s ignored (no server processes in the "
              "all-reduce kvstore)", file=sys.stderr)

    port = _free_port()
    procs = []
    for rank in range(args.num_workers):
        env = dict(os.environ)
        env.update({
            "DMLC_ROLE": "worker",
            "DMLC_NUM_WORKER": str(args.num_workers),
            "DMLC_WORKER_ID": str(rank),
            "DMLC_PS_ROOT_URI": "127.0.0.1",
            "DMLC_PS_ROOT_PORT": str(port),
        })
        procs.append(subprocess.Popen(args.command, env=env))

    # poll ALL workers: a high-rank crash must tear the job down even while
    # low ranks are blocked in a collective (rank-order wait() would hang)
    rc = 0
    try:
        while True:
            codes = [p.poll() for p in procs]
            failed = [c for c in codes if c not in (None, 0)]
            if failed and rc == 0:
                rc = failed[0]
                for q in procs:  # one worker died: tear the job down
                    if q.poll() is None:
                        q.send_signal(signal.SIGTERM)
            if all(c is not None for c in codes):
                return rc
            time.sleep(0.2)
    except KeyboardInterrupt:
        for q in procs:
            if q.poll() is None:
                q.send_signal(signal.SIGTERM)
        return 130


if __name__ == "__main__":
    sys.exit(main())
