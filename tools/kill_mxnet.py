#!/usr/bin/env python
"""Kill leftover distributed-job processes (reference: tools/kill-mxnet.py
— cleans up worker remnants after a crashed launch).

Finds processes whose environment carries the launcher's DMLC_* contract
(or whose command line matches the given script) and terminates them.

    python tools/kill_mxnet.py                 # kill all DMLC workers
    python tools/kill_mxnet.py train.py        # only workers running this
"""
from __future__ import annotations

import os
import signal
import sys


def _iter_procs():
    for pid in os.listdir("/proc"):
        if not pid.isdigit():
            continue
        try:
            with open(f"/proc/{pid}/environ", "rb") as f:
                env = f.read()
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmd = f.read().replace(b"\0", b" ").decode(
                    "utf-8", "replace")
        except (FileNotFoundError, PermissionError, ProcessLookupError):
            continue
        yield int(pid), env, cmd


def main():
    pattern = sys.argv[1] if len(sys.argv) > 1 else None
    me = os.getpid()
    killed = []
    for pid, env, cmd in _iter_procs():
        if pid == me:
            continue
        if b"DMLC_ROLE=worker" not in env:
            continue
        if pattern and pattern not in cmd:
            continue
        try:
            os.kill(pid, signal.SIGTERM)
            killed.append((pid, cmd.strip()))
        except ProcessLookupError:
            pass
    for pid, cmd in killed:
        print(f"killed {pid}: {cmd[:100]}")
    print(f"{len(killed)} process(es) terminated")


if __name__ == "__main__":
    main()
