#!/usr/bin/env python
"""Merge per-rank telemetry dumps into one deterministic fleet report.

A 3-worker dist run leaves three disjoint telemetry surfaces — three
jsonl logs, three crash reports, three live ops endpoints — and no way
to ask fleet-level questions ("which rank is the straggler?", "is rank
2 diverging?", "when did rank 1 die?") without hand-diffing files. This
tool is that missing merge:

    python tools/fleetstat.py rank0.jsonl rank1.jsonl rank2.jsonl
    python tools/fleetstat.py --scrape http://h0:9100 --scrape http://h1:9100
    python tools/fleetstat.py dumps/*.jsonl --json > FLEET.json

Inputs are auto-detected per file: a telemetry jsonl log (the ``meta``
first line carries rank/host/generation identity), a ``fleet.snapshot()``
JSON document, or a flight-recorder crash report. ``--scrape`` GETs
``/fleetz`` (+ ``/healthz``) from live ``telemetry.opsd`` endpoints.

The report is byte-identical across reruns of the same inputs (sorted
ranks, sorted series, no wall-clock reads):

* **per-rank step-time table** with cross-rank straggler attribution —
  which rank is slow, and which phase (data_wait/assemble/dispatch/
  device/other) carries the excess;
* **metric-divergence detection** — per-rank loss/eval-metric/grad-norm
  drift past a leave-one-out z-score threshold (a diverging rank means
  a bad data shard or silent corruption, not load);
* **training-health attribution** — each rank's ok/degraded/diverged
  state and fired rules from the ``train.health.*`` gauges, plus which
  rank's detector fired *first* (``train.health.first_firing`` carries
  the firing's step index, so the origin is ordered even after the
  blast radius trips every peer);
* **dead-rank timeline** — dump-staleness gaps (wall-clock meta),
  ``dead_node`` events from survivors, ``recovery.*`` counters and the
  re-exec generation per rank;
* **serving rollups** — fleet request/shed/queue/occupancy totals with
  per-rank breakdown.

The registry merge itself (counter sums, gauge min/max/mean, bucket-wise
histogram merge) is ``mxnet_tpu.telemetry.fleet.merge`` — this tool only
adapts file formats onto it and renders text. ``--json`` emits the
machine-readable document ``tools/perfwatch.py --fleet`` tracks
(``step.wall.p99_over_p50`` as a regression series).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

DEFAULT_Z = 3.0
DEFAULT_GAP_S = 30.0
STRAGGLER_PCT = 20.0     # mean-wall excess over fleet median that flags
_DISPERSION_FLOOR = 0.05  # leave-one-out z denominator floor (fraction)

# divergence is judged on correctness-shaped series only (loss, eval
# metrics, monitored tensors, anomaly trips, the training-health plane's
# live per-step stats) — load-shaped series (queue depths, walls)
# differ across ranks legitimately
_DIVERGENCE_GAUGES = ("monitor.stat", "train.health.grad_norm",
                      "train.health.update_ratio", "train.health.loss")
_DIVERGENCE_COUNTERS = ("sentinel.anomalies", "train.health.firings")

_HEALTH_STATE_NAMES = {0: "ok", 1: "degraded", 2: "diverged"}


def _fleet_mod():
    from mxnet_tpu.telemetry import fleet
    return fleet


def _fmt_us(us):
    us = float(us)
    if us < 1000:
        return f"{us:.0f} us"
    if us < 1e6:
        return f"{us / 1e3:.1f} ms"
    return f"{us / 1e6:.2f} s"


# ---------------------------------------------------------------- loading
def _blank_rank(source):
    return {"rank": 0, "host": "", "generation": 0, "num_workers": 1,
            "source": source, "time_unix": None,
            "counters": [], "gauges": [], "histograms": [],
            "events": [], "steps": [], "had_meta": False}


def _hist_from_jsonl(rec):
    """jsonl/crash histogram record ({'buckets': {str(le): cum}}) ->
    schema-v1 histogram fields (sorted bound/count lists)."""
    buckets = rec.get("buckets") or {}
    pairs = sorted(((float(le), c) for le, c in buckets.items()),
                   key=lambda p: p[0])
    return {"buckets": [le for le, _c in pairs],
            "bucket_counts": [c for _le, c in pairs],
            "count": rec.get("count", 0), "sum": rec.get("sum", 0.0),
            "min": rec.get("min"), "max": rec.get("max"),
            "exemplars": rec.get("exemplars") or {}}


def _parse_jsonl(text, source):
    r = _blank_rank(source)
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        t = rec.get("type")
        if t == "meta":
            r["rank"] = int(rec.get("rank", 0))
            r["host"] = rec.get("host", "")
            r["generation"] = int(rec.get("generation", 0))
            r["num_workers"] = int(rec.get("num_workers", 1))
            r["time_unix"] = rec.get("time_unix")
            r["had_meta"] = True
        elif t == "event":
            r["events"].append(rec)
        elif t == "step":
            r["steps"].append(rec)
        elif t == "counter":
            r["counters"].append({"name": rec.get("name", "?"),
                                  "labels": rec.get("labels") or {},
                                  "value": rec.get("value", 0)})
        elif t == "gauge":
            r["gauges"].append({"name": rec.get("name", "?"),
                                "labels": rec.get("labels") or {},
                                "value": rec.get("value", 0.0)})
        elif t == "histogram":
            r["histograms"].append(
                {"name": rec.get("name", "?"),
                 "labels": rec.get("labels") or {},
                 **_hist_from_jsonl(rec)})
    return r


def _parse_snapshot(doc, source):
    r = _blank_rank(source)
    r["rank"] = int(doc.get("rank", 0))
    r["host"] = doc.get("host", "")
    r["generation"] = int(doc.get("generation", 0))
    r["num_workers"] = int(doc.get("num_workers", 1))
    r["time_unix"] = doc.get("time_unix")
    r["counters"] = list(doc.get("counters", ()))
    r["gauges"] = list(doc.get("gauges", ()))
    r["histograms"] = list(doc.get("histograms", ()))
    r["had_meta"] = True
    return r


def _series_records(by_series):
    out = []
    for series, value in (by_series or {}).items():
        name, _, rest = series.partition("{")
        labels = {}
        for part in rest.rstrip("}").split(","):
            if "=" in part:
                k, _, v = part.partition("=")
                labels[k.strip()] = v.strip().strip('"')
        out.append({"name": name, "labels": labels, "value": value})
    return out


def _parse_crash(doc, source):
    r = _blank_rank(source)
    r["rank"] = int(doc.get("rank", 0))
    r["host"] = doc.get("host", "")
    r["time_unix"] = doc.get("time_unix")
    r["had_meta"] = "rank" in doc
    env = doc.get("env") or {}
    try:
        r["generation"] = int(env.get("MXNET_RECOVERY_GENERATION", 0) or 0)
    except ValueError:
        pass
    metrics = doc.get("metrics") or {}
    r["counters"] = _series_records(metrics.get("counters"))
    r["gauges"] = _series_records(metrics.get("gauges"))
    hists = []
    for series, rec in (metrics.get("histograms") or {}).items():
        name, _, rest = series.partition("{")
        labels = {}
        for part in rest.rstrip("}").split(","):
            if "=" in part:
                k, _, v = part.partition("=")
                labels[k.strip()] = v.strip().strip('"')
        hists.append({"name": name, "labels": labels,
                      **_hist_from_jsonl(rec)})
    r["histograms"] = hists
    # ring records double as the event feed (dead_node / recovery.*)
    for rec in doc.get("ring") or []:
        kind = rec.get("kind", "")
        if kind == "dead_node" or kind.startswith("recovery."):
            r["events"].append({"type": "event", "kind": kind, **{
                k: v for k, v in rec.items() if k != "kind"}})
    return r


def load_file(path):
    """One per-rank record from a jsonl log / snapshot / crash report."""
    with open(path) as f:
        text = f.read()
    source = os.path.basename(path)
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        doc = None
    if isinstance(doc, dict):
        if doc.get("type") == "crash_report":
            return _parse_crash(doc, source)
        if "counters" in doc and "schema" in doc:
            return _parse_snapshot(doc, source)
    return _parse_jsonl(text, source)


def scrape(url, timeout=5):
    """One per-rank record from a live ops endpoint (/fleetz +
    /healthz)."""
    import urllib.error
    import urllib.request

    base = url.rstrip("/")

    def get(route):
        try:
            with urllib.request.urlopen(base + route,
                                        timeout=timeout) as resp:
                return json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:     # /healthz is 503 when
            try:                                 # unhealthy — still JSON
                return json.loads(e.read().decode())
            except Exception:
                return None
        except Exception:
            return None

    snap = get("/fleetz")
    if snap is None:
        raise OSError(f"no /fleetz at {base}")
    r = _parse_snapshot(snap, base)
    health = get("/healthz")
    if health is not None:
        r["health"] = health
        for dead in health.get("kvstore", {}).get("dead_nodes", []):
            r["events"].append({"type": "event", "kind": "dead_node",
                                "ranks": [dead]})
    return r


# ---------------------------------------------------------------- analysis
def _pct(sorted_vals, q):
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1,
              int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def step_table(ranks, fleet):
    """Per-rank step stats + straggler attribution.

    Prefers the per-step ``step`` records (exact walls + phase split);
    falls back to the ``module.fit.batch.seconds`` histogram when a dump
    carries only the registry."""
    per_rank = {}
    for r in sorted(ranks, key=lambda x: x["rank"]):
        key = str(r["rank"])
        walls = sorted(s.get("wall_us", 0) / 1e3 for s in r["steps"])
        if walls:
            p50 = _pct(walls, 0.50)
            p99 = _pct(walls, 0.99)
            phases = {}
            for s in r["steps"]:
                for p, us in (s.get("phases_us") or {}).items():
                    phases[p] = phases.get(p, 0.0) + us / 1e3
            n = len(walls)
            per_rank[key] = {
                "steps": n, "p50_ms": p50, "p99_ms": p99,
                "mean_ms": sum(walls) / n,
                "p99_over_p50": (p99 / p50) if p50 else None,
                "phase_mean_ms": {p: v / n for p, v in
                                  sorted(phases.items())}}
            continue
        for h in r["histograms"]:
            if h["name"] == "module.fit.batch.seconds" and h["count"]:
                p50 = (fleet.hist_quantile(h, 0.50) or 0) * 1e3
                p99 = (fleet.hist_quantile(h, 0.99) or 0) * 1e3
                per_rank[key] = {
                    "steps": h["count"], "p50_ms": p50, "p99_ms": p99,
                    "mean_ms": (h["sum"] / h["count"]) * 1e3,
                    "p99_over_p50": (p99 / p50) if p50 else None,
                    "phase_mean_ms": {}}
                break
    doc = {"per_rank": per_rank, "spread_p99_over_p50": None,
           "spread_rank": None, "straggler": None}
    spreads = [(v["p99_over_p50"], k) for k, v in per_rank.items()
               if v["p99_over_p50"] is not None]
    if spreads:
        spread, rank = max(spreads)
        doc["spread_p99_over_p50"] = spread
        doc["spread_rank"] = rank
    # straggler: a rank whose mean wall sits past the fleet median
    means = sorted((v["mean_ms"], k) for k, v in per_rank.items())
    if len(means) >= 2:
        med = means[len(means) // 2][0] if len(means) % 2 else \
            (means[len(means) // 2 - 1][0] + means[len(means) // 2][0]) / 2
        worst_ms, worst = means[-1]
        if med > 0 and (worst_ms - med) / med * 100.0 >= STRAGGLER_PCT:
            excess_pct = (worst_ms - med) / med * 100.0
            phase, phase_pct = None, 0.0
            worst_phases = per_rank[worst]["phase_mean_ms"]
            for p, v in worst_phases.items():
                others = sorted(per_rank[k]["phase_mean_ms"].get(p, 0.0)
                                for k in per_rank if k != worst)
                base = _pct(others, 0.5) or 0.0
                delta = (v - base) / med * 100.0
                if delta > phase_pct:
                    phase, phase_pct = p, delta
            doc["straggler"] = {"rank": worst, "excess_pct": excess_pct,
                                "phase": phase, "phase_pct": phase_pct}
    return doc


def _divergence_values(ranks):
    """{series: {rank: value}} over the correctness-shaped surfaces."""
    out = {}
    for r in ranks:
        key = str(r["rank"])
        for rec in r["gauges"]:
            if rec["name"] in _DIVERGENCE_GAUGES:
                inner = ",".join(
                    f'{k}="{v}"' for k, v in sorted(rec["labels"].items()))
                series = rec["name"] + (f"{{{inner}}}" if inner else "")
                out.setdefault(series, {})[key] = float(rec["value"])
        for rec in r["counters"]:
            if rec["name"] in _DIVERGENCE_COUNTERS:
                out.setdefault(rec["name"], {})[key] = float(rec["value"])
        last = {}
        for e in r["events"]:
            if e.get("kind") != "epoch_end":
                continue
            for k, v in e.items():
                if k in ("type", "kind", "ts_us", "epoch"):
                    continue
                if "time" in k or k.endswith("_s"):
                    continue    # wall-time keys are load, not correctness
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    continue
                last[f"epoch_end.{k}"] = float(v)
        for series, v in last.items():
            out.setdefault(series, {})[key] = v
    return out


def divergence(ranks, z_threshold=DEFAULT_Z):
    """Leave-one-out z-score drift over loss/eval/monitor series.

    For each rank's value the reference is the *other* ranks' mean, and
    the denominator is their std floored at 5% of the reference mean —
    a plain z-score saturates at (n-1)/sqrt(n) for small fleets (3
    ranks cap at |z|=1.15), so an outlier could never cross a 3.0
    threshold; the leave-one-out form has no such cap."""
    flags = []
    for series, by_rank in sorted(_divergence_values(ranks).items()):
        if len(by_rank) < 3:
            continue
        for rank in sorted(by_rank, key=int):
            v = by_rank[rank]
            others = [by_rank[k] for k in by_rank if k != rank]
            mean = sum(others) / len(others)
            var = sum((o - mean) ** 2 for o in others) / len(others)
            floor = max(var ** 0.5, _DISPERSION_FLOOR * abs(mean), 1e-12)
            z = (v - mean) / floor
            if abs(z) >= z_threshold:
                flags.append({"series": series, "rank": rank,
                              "value": v, "fleet_mean": mean, "z": z})
    return flags


def train_health(ranks):
    """Per-rank training-health attribution from the ``train.health.*``
    gauges every snapshot/jsonl/crash dump carries: each rank's
    ok/degraded/diverged state, its fired rules, and — the question an
    operator actually asks — WHICH rank's detector fired first.
    ``train.health.first_firing{rule=...}`` records the observation
    (step) index of a rule's first firing on that rank, so the fleet
    minimum names the sick rank even when the blast radius later trips
    every peer."""
    doc = {"by_rank": {}, "first": None}
    for r in ranks:
        key = str(r["rank"])
        state = None
        rules = {}
        for rec in r["gauges"]:
            if rec["name"] == "train.health.state":
                state = int(rec["value"])
            elif rec["name"] == "train.health.first_firing":
                rule = rec["labels"].get("rule", "?")
                rules[rule] = int(rec["value"])
        if state is None and not rules:
            continue
        state = state or 0
        doc["by_rank"][key] = {
            "state": state,
            "name": _HEALTH_STATE_NAMES.get(state, str(state)),
            "rules": rules}
    firsts = [(n, key, rule)
              for key, rec in doc["by_rank"].items()
              for rule, n in rec["rules"].items()]
    if firsts:
        n, rank, rule = min(firsts)
        doc["first"] = {"rank": rank, "rule": rule, "observation": n}
    return doc


def dead_rank_timeline(ranks, gap_seconds=DEFAULT_GAP_S):
    """Stale dumps + survivor-reported deaths + recovery counters."""
    doc = {"stale_ranks": [], "lag_seconds": {}, "reported_dead": [],
           "events": [], "recovery": {}, "generations": {}}
    stamped = [(r["time_unix"], str(r["rank"])) for r in ranks
               if r["time_unix"] is not None]
    if stamped:
        newest = max(t for t, _r in stamped)
        for t, rank in sorted(stamped, key=lambda x: (x[1], x[0])):
            lag = newest - t
            doc["lag_seconds"][rank] = round(lag, 3)
            if lag > gap_seconds:
                doc["stale_ranks"].append(rank)
    reported = set()
    for r in sorted(ranks, key=lambda x: x["rank"]):
        for e in r["events"]:
            kind = e.get("kind", "")
            if kind == "dead_node" or kind.startswith("recovery."):
                dead = e.get("ranks") or e.get("dead") or []
                if isinstance(dead, (int, float, str)):
                    dead = [dead]
                reported.update(str(int(d)) for d in dead
                                if f"{d}".lstrip("-").isdigit())
                doc["events"].append(
                    {"observer": str(r["rank"]), "kind": kind,
                     **{k: v for k, v in e.items()
                        if k not in ("type", "kind", "ts_us")}})
        counts = {}
        for rec in r["counters"]:
            if rec["name"].startswith("recovery."):
                short = rec["name"][len("recovery."):]
                counts[short] = counts.get(short, 0) + rec["value"]
        if counts:
            doc["recovery"][str(r["rank"])] = counts
        doc["generations"][str(r["rank"])] = r["generation"]
    doc["reported_dead"] = sorted(reported, key=int)
    return doc


def serving_rollup(ranks, merged):
    """Fleet serving/decode rollups from the merged registry."""
    doc = {"counters": {}, "queue_depth_by_rank": {},
           "occupancy_mean": None}
    wanted = ("serve.requests", "serve.responses", "serve.shed",
              "serve.rejected", "serve.errors", "serve.decode.requests",
              "serve.decode.responses", "serve.decode.tokens",
              "serve.decode.migrations")
    for key, slot in merged.get("counters", {}).items():
        if slot["name"] in wanted:
            doc["counters"][key] = {"total": slot["total"],
                                    "by_rank": dict(slot["by_rank"])}
    occs = []
    for key, slot in merged.get("gauges", {}).items():
        if slot["name"].endswith("queue.depth"):
            for rank, v in slot["by_rank"].items():
                doc["queue_depth_by_rank"][rank] = \
                    doc["queue_depth_by_rank"].get(rank, 0) + v
        elif slot["name"] in ("serve.batch.occupancy",
                              "serve.decode.occupancy"):
            occs.extend(slot["by_rank"].values())
    if occs:
        doc["occupancy_mean"] = sum(occs) / len(occs)
    return doc


# ------------------------------------------------------------------ report
def build(ranks, z_threshold=DEFAULT_Z, gap_seconds=DEFAULT_GAP_S):
    """All analyses over loaded per-rank records -> one fleet document."""
    fleet = _fleet_mod()
    ranks = sorted(ranks, key=lambda r: (r["rank"], r["source"]))
    snaps = [{"schema": fleet.SCHEMA_VERSION, "rank": r["rank"],
              "host": r["host"], "num_workers": r["num_workers"],
              "generation": r["generation"], "counters": r["counters"],
              "gauges": r["gauges"], "histograms": r["histograms"]}
             for r in ranks]
    merged = fleet.merge(snaps)
    steps = step_table(ranks, fleet)
    doc = {
        "schema": fleet.SCHEMA_VERSION,
        "ranks": merged["ranks"],
        "sources": {str(r["rank"]): r["source"] for r in ranks},
        "hosts": merged["hosts"],
        "generations": {str(r["rank"]): r["generation"] for r in ranks},
        "step": steps,
        "divergence": divergence(ranks, z_threshold),
        "train_health": train_health(ranks),
        "dead": dead_rank_timeline(ranks, gap_seconds),
        "serving": serving_rollup(ranks, merged),
        "merged": merged,
        "series": {},
    }
    if steps["spread_p99_over_p50"] is not None:
        doc["series"]["step.wall.p99_over_p50"] = \
            steps["spread_p99_over_p50"]
    if doc["train_health"]["by_rank"]:
        # worst rank's health state as a tracked fleet series (0 ok /
        # 1 degraded / 2 diverged) — perfwatch --fleet flags any climb
        doc["series"]["train.health.state.max"] = float(max(
            rec["state"] for rec in doc["train_health"]["by_rank"].values()))
    return doc


def render(doc, z_threshold=DEFAULT_Z, gap_seconds=DEFAULT_GAP_S):
    """Fleet document -> deterministic report text."""
    out = ["=" * 64, f"FLEET REPORT — {len(doc['ranks'])} rank(s)",
           "=" * 64]
    for rank in doc["ranks"]:
        r = str(rank)
        out.append(f"rank {r}  host {doc['hosts'].get(r) or '?'}  "
                   f"gen {doc['generations'].get(r, 0)}  "
                   f"source {doc['sources'].get(r, '?')}")
    out.append("")

    steps = doc["step"]
    if steps["per_rank"]:
        out.append("step times:")
        out.append(f"  {'rank':<6}{'steps':>7}{'p50':>12}{'p99':>12}"
                   f"{'p99/p50':>10}")
        for rank in sorted(steps["per_rank"], key=int):
            s = steps["per_rank"][rank]
            spread = f"{s['p99_over_p50']:.2f}" \
                if s["p99_over_p50"] is not None else "?"
            out.append(
                f"  {rank:<6}{s['steps']:>7}"
                f"{_fmt_us(s['p50_ms'] * 1e3):>12}"
                f"{_fmt_us(s['p99_ms'] * 1e3):>12}{spread:>10}")
        if steps["spread_p99_over_p50"] is not None:
            out.append(f"  fleet spread: max p99/p50 "
                       f"{steps['spread_p99_over_p50']:.2f} "
                       f"(rank {steps['spread_rank']})")
        st = steps["straggler"]
        if st:
            phase = f" — dominated by {st['phase']} " \
                    f"(+{st['phase_pct']:.1f}% of median wall)" \
                if st["phase"] else ""
            out.append(f"  STRAGGLER: rank {st['rank']} "
                       f"+{st['excess_pct']:.1f}% mean wall vs fleet "
                       f"median{phase}")
        else:
            out.append("  no straggler flagged")
    else:
        out.append("step times: no step records or batch histograms")
    out.append("")

    out.append(f"metric divergence (leave-one-out |z| >= "
               f"{z_threshold:g}):")
    if doc["divergence"]:
        for f in doc["divergence"]:
            out.append(f"  RANK {f['rank']} DIVERGING: {f['series']} = "
                       f"{f['value']:g} vs fleet mean "
                       f"{f['fleet_mean']:g} (z={f['z']:+.1f})")
    else:
        out.append("  none")
    out.append("")

    th = doc.get("train_health") or {}
    if th.get("by_rank"):
        out.append("training health:")
        for rank in sorted(th["by_rank"], key=int):
            rec = th["by_rank"][rank]
            rules = ", ".join(
                f"{rule}@{rec['rules'][rule]}"
                for rule in sorted(rec["rules"],
                                   key=lambda x: rec["rules"][x])) \
                or "no rules fired"
            tag = rec["name"].upper() if rec["state"] else rec["name"]
            out.append(f"  rank {rank}: {tag} ({rules})")
        if th.get("first"):
            f = th["first"]
            out.append(f"  FIRST DIVERGED: rank {f['rank']} — "
                       f"{f['rule']} at observation {f['observation']}")
        out.append("")

    dead = doc["dead"]
    out.append("dead-rank timeline:")
    lines_before = len(out)
    for rank in sorted(dead["lag_seconds"], key=int):
        lag = dead["lag_seconds"][rank]
        if rank in dead["stale_ranks"]:
            out.append(f"  rank {rank}: last dump {lag:.1f}s behind the "
                       f"newest — STALE (heartbeat gap > "
                       f"{gap_seconds:g}s)")
        elif lag > 0:
            out.append(f"  rank {rank}: last dump {lag:.1f}s behind "
                       f"the newest")
    if dead["reported_dead"]:
        out.append(f"  reported dead by survivors: rank(s) "
                   f"{', '.join(dead['reported_dead'])}")
    for e in dead["events"][:8]:
        desc = {k: v for k, v in e.items() if k not in ("observer",
                                                        "kind")}
        out.append(f"  rank {e['observer']} saw {e['kind']} {desc}")
    for rank in sorted(dead["recovery"], key=int):
        counts = dead["recovery"][rank]
        inner = ", ".join(f"{k}={int(v)}" for k, v in
                          sorted(counts.items()))
        out.append(f"  rank {rank} recovery counters: {inner}")
    gens = {r: g for r, g in dead["generations"].items() if g}
    if gens:
        out.append("  re-exec generations: " + ", ".join(
            f"rank {r} gen {gens[r]}" for r in sorted(gens, key=int)))
    if len(out) == lines_before:
        out.append("  all ranks current; no deaths reported")
    out.append("")

    serving = doc["serving"]
    if (serving["counters"] or serving["queue_depth_by_rank"] or
            serving["occupancy_mean"] is not None):
        out.append("serving rollup:")
        for key in sorted(serving["counters"]):
            slot = serving["counters"][key]
            per = ", ".join(
                f"rank {r}: {slot['by_rank'][r]:g}"
                for r in sorted(slot["by_rank"], key=int))
            out.append(f"  {key}: {slot['total']:g} ({per})")
        if serving["queue_depth_by_rank"]:
            per = ", ".join(
                f"rank {r}: {serving['queue_depth_by_rank'][r]:g}"
                for r in sorted(serving["queue_depth_by_rank"], key=int))
            out.append(f"  queue depth: {per}")
        if serving["occupancy_mean"] is not None:
            out.append(f"  occupancy mean: "
                       f"{serving['occupancy_mean']:.1%}")
        out.append("")

    fleet = _fleet_mod()
    wall = None
    for key, slot in doc["merged"]["histograms"].items():
        if slot["name"] == "module.fit.batch.seconds":
            wall = slot["merged"]
    if wall and wall["count"]:
        p50 = fleet.hist_quantile(wall, 0.50)
        p99 = fleet.hist_quantile(wall, 0.99)
        out.append(f"fleet batch wall (merged histogram): p50 "
                   f"{_fmt_us((p50 or 0) * 1e6)} / p99 "
                   f"{_fmt_us((p99 or 0) * 1e6)} over "
                   f"{wall['count']} batches")
    n_series = (len(doc["merged"]["counters"]) +
                len(doc["merged"]["gauges"]) +
                len(doc["merged"]["histograms"]))
    out.append(f"merged registry: {n_series} series across "
               f"{len(doc['ranks'])} rank(s)")
    return "\n".join(out)


# -------------------------------------------------------------------- main
def main(argv=None):
    p = argparse.ArgumentParser(
        description="Merge per-rank telemetry dumps (jsonl / snapshot / "
                    "crash report) or live endpoints into one fleet "
                    "report.")
    p.add_argument("files", nargs="*",
                   help="per-rank dump files (format auto-detected)")
    p.add_argument("--scrape", action="append", default=[],
                   metavar="URL",
                   help="live ops endpoint base URL (repeatable)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit the machine-readable fleet document "
                        "(perfwatch --fleet reads it)")
    p.add_argument("--z-threshold", type=float, default=DEFAULT_Z,
                   help=f"divergence flag threshold "
                        f"(default {DEFAULT_Z})")
    p.add_argument("--gap-seconds", type=float, default=DEFAULT_GAP_S,
                   help=f"dump staleness considered a heartbeat gap "
                        f"(default {DEFAULT_GAP_S:g}s)")
    args = p.parse_args(argv)
    if not args.files and not args.scrape:
        p.error("give dump files and/or --scrape URLs")

    ranks = []
    for path in args.files:
        try:
            ranks.append(load_file(path))
        except OSError as e:
            print(f"fleetstat: {path}: {e}", file=sys.stderr)
            return 2
    for url in args.scrape:
        try:
            ranks.append(scrape(url))
        except OSError as e:
            print(f"fleetstat: {e}", file=sys.stderr)
            return 2
    if not ranks:
        print("fleetstat: nothing loaded", file=sys.stderr)
        return 2

    doc = build(ranks, z_threshold=args.z_threshold,
                gap_seconds=args.gap_seconds)
    if args.as_json:
        slim = {k: v for k, v in doc.items() if k != "merged"}
        print(json.dumps(slim, indent=2, sort_keys=True))
    else:
        print(render(doc, z_threshold=args.z_threshold,
                     gap_seconds=args.gap_seconds))
    return 0


if __name__ == "__main__":
    sys.exit(main())
