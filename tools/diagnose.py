#!/usr/bin/env python
"""Render a crash report or telemetry jsonl log as a health report.

The operator-facing half of the diagnostics layer: the flight recorder
(mxnet_tpu.telemetry.flightrec) leaves ``mxnet_crash_*.json`` dumps when
a run dies, and ``mx.telemetry.jsonl.dump()`` writes the structured
event log of a live run — this tool turns either into the summary a
human reads first:

* what killed the run (exception + where + recent-activity timeline),
* throughput trend across the run (is it slowing down?),
* slowest ops / dispatch latencies,
* jit-cache hit rate (recompilation storms),
* per-context memory watermarks (how close to OOM),
* the first-anomaly timeline from the NaN/Inf sentinel.

Usage:
    python tools/diagnose.py mxnet_crash_12345_1.json
    python tools/diagnose.py train_events.jsonl [--top 10]

Input format is auto-detected: a single JSON object with
``"type": "crash_report"`` takes the crash path, anything else is
treated as a JSON-lines event log.
"""
from __future__ import annotations

import argparse
import json
import sys


def _fmt_bytes(n):
    if n is None:
        return "?"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024


def _fmt_us(us):
    us = float(us)
    if us < 1000:
        return f"{us:.0f} us"
    if us < 1e6:
        return f"{us / 1e3:.1f} ms"
    return f"{us / 1e6:.2f} s"


def _strip_labels(series):
    """'name{k="v"}' -> (name, 'k="v"')."""
    if "{" in series:
        name, _, rest = series.partition("{")
        return name, rest.rstrip("}")
    return series, ""


# ------------------------------------------------------------ shared bits
def _jit_cache_section(counters):
    hits = sum(v for k, v in counters.items()
               if _strip_labels(k)[0] == "executor.jit_cache.hit")
    misses = sum(v for k, v in counters.items()
                 if _strip_labels(k)[0] == "executor.jit_cache.miss")
    total = hits + misses
    if not total:
        return ["jit cache: no activity recorded"]
    rate = 100.0 * hits / total
    lines = [f"jit cache: {hits}/{total} hits ({rate:.1f}% hit rate, "
             f"{misses} compiles)"]
    if misses > hits and total > 4:
        lines.append("  WARNING: more compiles than cache hits — "
                     "recompilation storm (varying shapes/dtypes?)")
    return lines


def _memory_section(mem):
    lines = []
    for ctx in sorted(mem or {}):
        rec = mem[ctx]
        live, peak = rec.get("live_bytes"), rec.get("peak_bytes")
        extra = ""
        if rec.get("allocs") is not None:
            extra = (f"  ({rec.get('allocs', 0)} allocs, "
                     f"{rec.get('frees', 0)} frees)")
        lines.append(f"  {ctx}: live {_fmt_bytes(live)}, "
                     f"peak {_fmt_bytes(peak)}{extra}")
    return ["memory watermarks:"] + (lines or ["  (no accounting data)"])


def _lint_section(counters, lint_records):
    """Static-analysis findings: counter totals per rule plus the most
    recent finding records mirrored into the flight-recorder ring by
    mxnet_tpu.analysis (bind-time validation / mxlint)."""
    per_rule = {}
    for series, val in (counters or {}).items():
        name, labels = _strip_labels(series)
        if name != "analysis.lint.findings":
            continue
        rule = "?"
        for part in labels.split(","):
            if part.startswith("rule="):
                rule = part.split("=", 1)[1].strip('"')
        per_rule[rule] = per_rule.get(rule, 0) + val
    if not per_rule and not lint_records:
        return ["lint findings: none recorded"]
    total = int(sum(per_rule.values())) or len(lint_records)
    lines = [f"lint findings: {total} recorded "
             f"({', '.join(f'{r} x{int(n)}' for r, n in sorted(per_rule.items()))})"
             if per_rule else f"lint findings: {total} recorded"]
    for r in lint_records[-5:]:
        node = f" at '{r['node']}'" if r.get("node") else ""
        lines.append(f"  {r.get('rule', '?')} [{r.get('severity', '?')}]"
                     f"{node}: {r.get('message', '')}")
    if per_rule:
        lines.append("  (rule catalog: docs/analysis.md; "
                     "python tools/mxlint.py --rules)")
    return lines


def _memplan_section(gauges, records):
    """Static memory-plan report from the memplan.* gauges
    (analysis/memplan.py: mxlint --memory-plan, exec_group.
    static_memory_plan, or the armed memory_planner lint pass) plus the
    memplan.plan flight-ring notes. Rendered only when plans exist."""
    plans = {}
    for name, labels, val in gauges:
        if not name.startswith("memplan."):
            continue
        key = (labels.get("model", ""), labels.get("policy", "?"))
        plans.setdefault(key, {})[name[len("memplan."):]] = val
    if not plans and not records:
        return []
    lines = ["memory plan (static, pre-compile):"]
    for (model, policy), rec in sorted(plans.items()):
        tag = f"{model} " if model else ""
        parts = [f"{tag}policy={policy}"]
        if "peak_bytes_per_device" in rec:
            parts.append(
                f"peak {_fmt_bytes(rec['peak_bytes_per_device'])}/dev")
        if "residual_bytes" in rec:
            parts.append(f"residuals {_fmt_bytes(rec['residual_bytes'])}")
        if "param_bytes" in rec:
            parts.append(f"params {_fmt_bytes(rec['param_bytes'])}")
        if "batch_bytes" in rec:
            parts.append(f"batch {_fmt_bytes(rec['batch_bytes'])}")
        lines.append("  " + ", ".join(parts))
    for r in (records or [])[-3:]:
        lines.append(f"  planned: {r.get('model') or 'binding'} "
                     f"policy={r.get('policy', '?')} "
                     f"batch={r.get('batch', '?')} -> peak "
                     f"{_fmt_bytes(r.get('peak_bytes', 0))}")
    lines.append("  (predict OOM before compile: "
                 "python tools/mxlint.py --memory-plan <model>)")
    return lines


def _fmt_flops(f):
    f = float(f)
    for unit in ("FLOP/s", "kFLOP/s", "MFLOP/s", "GFLOP/s", "TFLOP/s"):
        if abs(f) < 1000 or unit == "TFLOP/s":
            return f"{f:.1f} {unit}"
        f /= 1000


def _roofline_section(gauges, spans, top=8):
    """MFU/roofline report from the mfu.* gauges (telemetry/mfu.py).

    ``gauges`` is an iterable of (name, labels_dict, value). When op.*
    spans carry real per-op wall time (NaiveEngine / monitored runs),
    achieved FLOP/s per op is derived from them; under jit the per-op
    rows are static attribution (share of step FLOPs + roofline bound).
    """
    per_op = {}
    model = {}
    for name, labels, val in gauges:
        if name.startswith("mfu.op."):
            op = labels.get("op", "?")
            per_op.setdefault(op, {})[name.rsplit(".", 1)[-1]] = val
        elif name.startswith("mfu."):
            model[name] = val
    if not per_op and not model:
        return ["roofline/MFU: no mfu.* gauges recorded "
                "(telemetry off, or no cost metadata)"]
    lines = ["roofline / MFU:"]
    if "mfu.model" in model:
        ach = model.get("mfu.achieved_flops_per_sec")
        lines.append(f"  model MFU {model['mfu.model'] * 100:.1f}% of peak"
                     + (f" (achieved {_fmt_flops(ach)})" if ach else ""))
    elif "mfu.achieved_flops_per_sec" in model:
        lines.append("  achieved "
                     f"{_fmt_flops(model['mfu.achieved_flops_per_sec'])} "
                     "(no peak known for this device; MFU withheld)")
    if "mfu.node_coverage" in model:
        cov = model["mfu.node_coverage"]
        note = "" if cov >= 0.9 else \
            "  — LOW: run tools/mxlint.py --mfu-audit"
        lines.append(f"  cost-metadata coverage: {cov * 100:.0f}% of "
                     f"compute nodes{note}")
    # real per-op wall time, when the run executed eagerly
    op_secs = {}
    for s in spans or []:
        name = s.get("name", "")
        if name.startswith("op."):
            op_secs[name[3:]] = op_secs.get(name[3:], 0.0) + \
                s.get("dur_us", 0) / 1e6
    total = sum(r.get("flops", 0.0) for r in per_op.values()) or 1.0
    rows = sorted(per_op.items(), key=lambda kv: -kv[1].get("flops", 0))
    for op, rec in rows[:top]:
        ai = rec.get("ai")
        line = (f"  {op:<20} {rec.get('flops', 0) / total * 100:5.1f}% of "
                f"FLOPs")
        if ai is not None:
            bound = "compute-bound" if ai >= 100 else "memory-bound"
            line += f", AI {ai:7.1f} ({bound})"
        if op in op_secs and op_secs[op] > 0 and rec.get("flops"):
            line += f", achieved {_fmt_flops(rec['flops'] / op_secs[op])}"
        lines.append(line)
    return lines


def _gauge_triples_from_series(gauges_by_series):
    """{'name{k="v"}': value} -> [(name, labels_dict, value)]."""
    out = []
    for series, val in (gauges_by_series or {}).items():
        name, labelstr = _strip_labels(series)
        labels = {}
        for part in labelstr.split(","):
            if "=" in part:
                k, _, v = part.partition("=")
                labels[k.strip()] = v.strip().strip('"')
        out.append((name, labels, val))
    return out


def _hist_entries_from_series(hists_by_series):
    """{'name{k="v"}': rec} -> [(name, labels_dict, rec)]."""
    out = []
    for series, rec in (hists_by_series or {}).items():
        name, labelstr = _strip_labels(series)
        labels = {}
        for part in labelstr.split(","):
            if "=" in part:
                k, _, v = part.partition("=")
                labels[k.strip()] = v.strip().strip('"')
        out.append((name, labels, rec))
    return out


def _hist_quantile(rec, q):
    """Estimated q-quantile from a histogram record's cumulative
    ``buckets`` (the metrics.Histogram.quantile math, replayed offline
    over a jsonl/crash snapshot). None without bucket data."""
    count = rec.get("count") or 0
    buckets = rec.get("buckets") or {}
    if not count or not buckets:
        return None
    rank = q * count
    prev_le, prev_cum = 0.0, 0
    for le_s, cum in sorted(buckets.items(), key=lambda kv: float(kv[0])):
        le = float(le_s)
        if cum >= rank:
            if cum == prev_cum:
                return le
            return prev_le + (rank - prev_cum) / (cum - prev_cum) * \
                (le - prev_le)
        prev_le, prev_cum = le, cum
    return rec.get("max")


def _serving_section(counters, gauge_triples, hist_entries):
    """Serving health (mxnet_tpu/serve): per-model p50/p99 latency,
    queue depth, batch occupancy, padding waste, deadline misses —
    rendered only when serve.* series exist in the log."""
    lat = {}                        # model -> latency histogram record
    for name, labels, rec in hist_entries:
        if name == "serve.request.latency.seconds":
            lat[labels.get("model", "?")] = rec
    gauges = {}
    for name, labels, val in gauge_triples:
        if name.startswith("serve."):
            gauges[(name, labels.get("model"))] = val
    ctr = {}
    for series, val in (counters or {}).items():
        name, labelstr = _strip_labels(series)
        if not name.startswith("serve."):
            continue
        model = None
        for part in labelstr.split(","):
            if part.strip().startswith("model="):
                model = part.partition("=")[2].strip().strip('"')
        ctr[(name, model)] = ctr.get((name, model), 0) + val
    if not (lat or gauges or ctr):
        return []

    models = sorted({m for (_, m) in list(ctr) + list(gauges)
                     if m is not None} | set(lat))
    lines = ["serving:"]
    for m in models:
        rec = lat.get(m)
        if rec and rec.get("count"):
            p50 = _hist_quantile(rec, 0.50)
            p99 = _hist_quantile(rec, 0.99)
            ltxt = (f"p50 {_fmt_us((p50 or 0) * 1e6)} / "
                    f"p99 {_fmt_us((p99 or 0) * 1e6)}"
                    if p50 is not None else
                    f"mean {_fmt_us((rec.get('mean') or 0) * 1e6)}")
            ltxt += f" over {rec['count']} reqs"
        else:
            ltxt = "no latency data"
        rows = ctr.get(("serve.rows", m), 0)
        padded = ctr.get(("serve.padded_rows", m), 0)
        occ = f"{rows / padded:.0%} occupancy, " \
              f"{100 * (1 - rows / padded):.1f}% padding waste" \
            if padded else "no dispatches"
        depth = gauges.get(("serve.queue.depth", m))
        extras = []
        if depth is not None:
            extras.append(f"queue depth {depth:.0f}")
        misses = ctr.get(("serve.deadline.miss", m), 0)
        if misses:
            extras.append(f"{misses:.0f} DEADLINE MISSES")
        rejected = ctr.get(("serve.rejected", m), 0)
        if rejected:
            extras.append(f"{rejected:.0f} rejected")
        errors = ctr.get(("serve.errors", m), 0)
        if errors:
            extras.append(f"{errors:.0f} dispatch ERRORS")
        lines.append(f"  model {m}: {ltxt}; {occ}"
                     + ("; " + ", ".join(extras) if extras else ""))
    compiles = gauges.get(("serve.program_cache.compiles_since_warmup",
                           None))
    if compiles is not None:
        flag = "" if not compiles else \
            "  WARNING: serving is compiling in steady state"
        lines.append(f"  compiles since warmup: {compiles:.0f}{flag}")
    return lines


def _decode_section(counters, gauge_triples, hist_entries):
    """Continuous-decode engine health (mxnet_tpu/serve/decode): slot
    occupancy, queue depth, join/leave/migration churn, per-iteration
    step time and request latency — rendered only when serve.decode.*
    series exist. Both the crash path and the jsonl path call this."""
    gauges = {}
    for name, labels, val in gauge_triples:
        if name.startswith("serve.decode."):
            gauges[(name[len("serve.decode."):],
                    labels.get("model", "?"))] = val
    ctr = {}
    for series, val in (counters or {}).items():
        name, labelstr = _strip_labels(series)
        if not name.startswith("serve.decode."):
            continue
        model = "?"
        for part in labelstr.split(","):
            if part.strip().startswith("model="):
                model = part.partition("=")[2].strip().strip('"')
        key = (name[len("serve.decode."):], model)
        ctr[key] = ctr.get(key, 0) + val
    hists = {}
    for name, labels, rec in hist_entries:
        if name.startswith("serve.decode."):
            hists[(name[len("serve.decode."):],
                   labels.get("model", "?"))] = rec
    if not (gauges or ctr or hists):
        return []

    models = sorted({m for (_k, m) in
                     list(gauges) + list(ctr) + list(hists)})
    lines = ["decode engine (continuous batching):"]
    for m in models:
        slots = gauges.get(("slots", m))
        active = gauges.get(("active", m))
        occ = gauges.get(("occupancy", m))
        head = f"  model {m}:"
        if slots is not None:
            head += f" {active or 0:.0f}/{slots:.0f} slots active"
            if occ is not None:
                head += f" ({occ:.0%} occupancy)"
        depth = gauges.get(("queue.depth", m))
        if depth is not None:
            head += f", queue depth {depth:.0f}"
        lines.append(head)
        reqs = ctr.get(("requests", m), 0)
        resps = ctr.get(("responses", m), 0)
        errors = ctr.get(("errors", m), 0)
        if reqs or resps:
            lines.append(f"    sessions: {reqs:.0f} admitted, "
                         f"{resps:.0f} completed"
                         + (f", {errors:.0f} ERRORS" if errors else ""))
        iters = ctr.get(("iterations", m), 0)
        tokens = ctr.get(("tokens", m), 0)
        if iters:
            lines.append(f"    iterations: {iters:.0f} "
                         f"({tokens:.0f} tokens, "
                         f"{tokens / iters:.2f} tokens/iteration)")
        joins = ctr.get(("joins", m), 0)
        leaves = ctr.get(("leaves", m), 0)
        migrations = ctr.get(("migrations", m), 0)
        if joins or leaves or migrations:
            lines.append(f"    churn: {joins:.0f} joins, "
                         f"{leaves:.0f} leaves, "
                         f"{migrations:.0f} rung migration(s)")
        step = hists.get(("step.seconds", m))
        if step and step.get("count"):
            p50 = _hist_quantile(step, 0.50)
            p99 = _hist_quantile(step, 0.99)
            lines.append(
                f"    step time: p50 {_fmt_us((p50 or 0) * 1e6)} / "
                f"p99 {_fmt_us((p99 or 0) * 1e6)} over "
                f"{step['count']} iterations")
        lat = hists.get(("request.latency.seconds", m))
        if lat and lat.get("count"):
            p50 = _hist_quantile(lat, 0.50)
            p99 = _hist_quantile(lat, 0.99)
            lines.append(
                f"    session latency: p50 {_fmt_us((p50 or 0) * 1e6)} / "
                f"p99 {_fmt_us((p99 or 0) * 1e6)} over "
                f"{lat['count']} sessions")
    return lines


def _checkpoint_section(counters, gauge_triples, hist_entries, records):
    """Checkpoint / recovery health (mxnet_tpu/checkpoint): snapshot
    cadence + commit count, exposed stall vs background write cost,
    last committed sequence, and a dead-node/recovery event timeline —
    rendered only when ckpt.*/recovery.* series or records exist."""
    ctr = {_strip_labels(k)[0]: v for k, v in (counters or {}).items()}
    snaps = ctr.get("ckpt.snapshots", 0)
    commits = ctr.get("ckpt.commits", 0)
    failures = ctr.get("ckpt.failures", 0)
    rec_events = ctr.get("recovery.events", 0)
    hists = {name: rec for name, _labels, rec in hist_entries}
    stall = hists.get("ckpt.exposed_stall.seconds")
    write = hists.get("ckpt.snapshot.seconds")
    last_seq = None
    for name, _labels, val in gauge_triples:
        if name == "ckpt.last_seq":
            last_seq = val
    ckpt_records = [r for r in (records or [])
                    if str(r.get("kind", "")).startswith(("ckpt.",
                                                          "recovery."))
                    or r.get("kind") == "dead_node"]
    if not (snaps or commits or rec_events or stall or ckpt_records):
        return []

    lines = ["checkpoint / recovery:"]
    head = (f"  snapshots: {snaps:.0f} taken, {commits:.0f} committed"
            + (f", {failures:.0f} FAILED" if failures else ""))
    if last_seq is not None:
        head += f"; last committed seq {last_seq:.0f}"
    lines.append(head)
    if stall and stall.get("count"):
        lines.append(
            f"  exposed stall: mean "
            f"{_fmt_us((stall.get('mean') or 0) * 1e6)} / max "
            f"{_fmt_us((stall.get('max') or 0) * 1e6)} per snapshot "
            f"(training-thread cost)")
    if write and write.get("count"):
        lines.append(
            f"  background write: mean "
            f"{_fmt_us((write.get('mean') or 0) * 1e6)} per snapshot "
            f"(writer thread: D2H + serialize + fsync + commit)")
    if rec_events:
        lines.append(f"  RECOVERY: {rec_events:.0f} dead-node "
                     f"detection(s)")
    timeline = [r for r in ckpt_records
                if str(r.get("kind", "")).startswith("recovery.")
                or r.get("kind") == "dead_node"]
    for r in timeline[:6]:
        desc = {k: v for k, v in r.items()
                if k not in ("kind", "ts_us")}
        lines.append(f"    {r.get('kind', '?')} {desc}")
    commits_r = [r for r in ckpt_records if r.get("kind") ==
                 "ckpt.commit"]
    if commits_r:
        spread = (commits_r[-1].get("ts_us", 0) -
                  commits_r[0].get("ts_us", 0)) / 1e6
        if len(commits_r) > 1 and spread > 0:
            lines.append(f"  cadence: {len(commits_r)} commits in ring, "
                         f"~every {spread / (len(commits_r) - 1):.1f}s")
        last = commits_r[-1]
        lines.append(f"  last commit: seq {last.get('seq', '?')} at "
                     f"epoch {last.get('epoch', '?')}, batch "
                     f"{last.get('nbatch', '?')}")
    return lines


_BREAKER_STATES = {0: "closed", 1: "half-open", 2: "OPEN"}

_FAULT_RECORD_KINDS = ("fault.injected", "retry.attempt", "retry.giveup",
                       "serve.shed", "serve.breaker.transition",
                       "io.decode.skip", "ckpt.quarantine", "ckpt.damaged")


def _faults_section(counters, gauge_triples, records):
    """Fault-plane / degradation health (mxnet_tpu/faults, docs/
    faults.md): injections fired per point, retry totals per site,
    circuit-breaker states and transitions, shed counts, decode skips,
    quarantined/damaged checkpoints — rendered only when any of it
    happened."""
    def _by_label(metric, label):
        out = {}
        for series, val in (counters or {}).items():
            name, labelstr = _strip_labels(series)
            if name != metric:
                continue
            key = "?"
            for part in labelstr.split(","):
                if part.strip().startswith(f"{label}="):
                    key = part.partition("=")[2].strip().strip('"')
            out[key] = out.get(key, 0) + val
        return out

    injected = _by_label("faults.injected", "point")
    attempts = _by_label("retry.attempts", "site")
    retries = _by_label("retry.retries", "site")
    giveups = _by_label("retry.giveups", "site")
    shed = _by_label("serve.shed", "model")
    transitions = _by_label("serve.breaker.transitions", "to")
    breaker_state = {}
    for name, labels, val in gauge_triples:
        if name == "serve.breaker.state":
            breaker_state[labels.get("model", "?")] = val
    flat = {_strip_labels(k)[0]: v for k, v in (counters or {}).items()}
    skipped = flat.get("io.decode.skipped", 0)
    quarantined = flat.get("ckpt.quarantined", 0)
    damaged = flat.get("ckpt.damaged", 0)
    fault_records = [r for r in (records or [])
                     if r.get("kind") in _FAULT_RECORD_KINDS]
    open_breakers = {m: v for m, v in breaker_state.items() if v}

    if not (injected or retries or giveups or shed or transitions or
            skipped or quarantined or damaged or fault_records or
            open_breakers):
        return []

    lines = ["faults / degradation:"]
    if injected:
        total = int(sum(injected.values()))
        lines.append(
            f"  injections fired: {total} "
            f"({', '.join(f'{p} x{int(n)}' for p, n in sorted(injected.items()))})")
    for site in sorted(set(retries) | set(giveups)):
        lines.append(
            f"  retries [{site}]: {int(retries.get(site, 0))} retried "
            f"over {int(attempts.get(site, 0))} attempts"
            + (f", {int(giveups[site])} GAVE UP"
               if giveups.get(site) else ""))
    for m in sorted(breaker_state):
        state = _BREAKER_STATES.get(int(breaker_state[m]),
                                    breaker_state[m])
        if breaker_state[m] or transitions:
            lines.append(f"  breaker [{m}]: {state}"
                         + (f" ({int(transitions.get('open', 0))} trips)"
                            if transitions.get("open") else ""))
    for m, n in sorted(shed.items()):
        lines.append(f"  load shed [{m}]: {int(n)} request(s) "
                     "(doomed-deadline shedding)")
    if skipped:
        lines.append(f"  decode skips: {int(skipped)} batch(es) "
                     "skipped-with-record")
    if quarantined:
        lines.append(f"  checkpoint: {int(quarantined)} seq(s) "
                     "QUARANTINED after retries")
    if damaged:
        lines.append(f"  checkpoint: {int(damaged)} damaged commit(s) "
                     "skipped at restore")
    for r in fault_records[-5:]:
        desc = {k: v for k, v in r.items() if k not in ("kind", "ts_us")}
        lines.append(f"    {r.get('kind', '?')} {desc}")
    return lines


def _traces_section(trace_recs, counters, hist_entries, straggler_recs,
                    top=3):
    """Trace-plane report (telemetry.trace + stepattr): the slowest
    request span trees, the step-phase breakdown table, and the
    straggler list — rendered only when trace/step data exists."""
    # --- request trace trees: dedupe by (trace, span), last wins
    by_key = {}
    for r in trace_recs or []:
        if r.get("trace") is None or r.get("span") is None:
            continue
        by_key[(r["trace"], r["span"])] = r
    by_trace = {}
    for r in by_key.values():
        by_trace.setdefault(r["trace"], []).append(r)

    phase_rows = []
    for name, _labels, rec in hist_entries or []:
        if name.startswith("step.phase.") and name.endswith(".seconds"):
            phase_rows.append((name[len("step.phase."):-len(".seconds")],
                               rec))
    stragglers = int({_strip_labels(k)[0]: v
                      for k, v in (counters or {}).items()}
                     .get("step.stragglers", 0))

    if not (by_trace or phase_rows or straggler_recs or stragglers):
        return []
    lines = ["traces:"]

    roots = []
    for tid, recs in by_trace.items():
        spans = {r["span"] for r in recs}
        for r in recs:
            if r.get("parent") is None or r["parent"] not in spans:
                roots.append((tid, r))
                break
    roots.sort(key=lambda tr: -(tr[1].get("dur_us") or 0))
    if roots:
        lines.append(f"  request traces: {len(by_trace)} in "
                     f"buffer/ring; slowest:")

    def render_node(recs, node, depth):
        extra = ""
        if node.get("error"):
            extra = f"  ERROR={node['error']}"
        elif node.get("deadline_miss"):
            extra = "  DEADLINE MISS"
        lines.append(f"  {'  ' * depth}{_fmt_us(node.get('dur_us', 0)):>10}"
                     f"  {node.get('name', '?')}{extra}")
        kids = sorted((r for r in recs
                       if r.get("parent") == node["span"]),
                      key=lambda r: r.get("ts_us", 0))
        for k in kids:
            render_node(recs, k, depth + 1)

    for tid, root in roots[:top]:
        lines.append(f"    {tid}:")
        render_node(by_trace[tid], root, 2)

    if phase_rows:
        total = sum((rec.get("sum") or 0.0) for _p, rec in phase_rows)
        lines.append("  step phases (per logical batch):")
        for phase, rec in sorted(
                phase_rows, key=lambda pr: -(pr[1].get("sum") or 0)):
            mean = (rec.get("mean") or 0.0) * 1e3
            share = 100.0 * (rec.get("sum") or 0.0) / total if total \
                else 0.0
            lines.append(f"    {phase:<10} mean {mean:8.2f} ms  "
                         f"{share:5.1f}% of step  "
                         f"(n={rec.get('count', 0)})")
    if stragglers or straggler_recs:
        n = stragglers or len(straggler_recs or [])
        lines.append(f"  stragglers: {int(n)} step(s) flagged "
                     f"(> k*MAD above rolling median)")
        for r in (straggler_recs or [])[-3:]:
            phases = {k[:-3]: _fmt_us(v) for k, v in r.items()
                      if k.endswith("_us") and
                      k not in ("ts_us", "wall_us", "median_us")}
            lines.append(
                f"    epoch {r.get('epoch', '?')} batch "
                f"{r.get('nbatch', '?')}: {_fmt_us(r.get('wall_us', 0))}"
                f" vs median {_fmt_us(r.get('median_us', 0))} — "
                f"{phases}")
    return lines


def _anomaly_section(anoms):
    if not anoms:
        return ["anomalies: none recorded"]
    anoms = sorted(anoms, key=lambda a: a.get("ts_us", 0))

    def where(a):
        # sentinel records stamp the active request trace id: the
        # first-NaN joins its span tree in the traces section below
        tid = a.get("trace")
        return f" (trace {tid})" if tid else ""

    first = anoms[0]
    lines = [f"anomalies: {len(anoms)} non-finite detections "
             f"(NaN/Inf sentinel)"]
    lines.append(f"  FIRST: {first.get('what', first.get('kind', '?'))} "
                 f"{first.get('array', '?')!r} at step "
                 f"{first.get('step', '?')}{where(first)}")
    for a in anoms[1:6]:
        lines.append(f"  then:  {a.get('what', a.get('kind', '?'))} "
                     f"{a.get('array', '?')!r} at step "
                     f"{a.get('step', '?')}{where(a)}")
    if len(anoms) > 6:
        lines.append(f"  ... and {len(anoms) - 6} more")
    return lines


_HEALTH_STATES = {0: "ok", 1: "degraded", 2: "diverged"}


def _train_health_section(counters, gauge_triples, records):
    """Training-health plane report (telemetry/health.py): state, the
    rule-firing timeline, the final stat-series values, and any
    emergency-checkpoint commits the triage ladder landed. ``records``
    are the ``train.health`` / ``train.health.ckpt`` flight-ring
    records (crash path) or core events (jsonl path)."""
    state = None
    tails = {}
    for name, labels, val in gauge_triples or []:
        if name == "train.health.state":
            state = int(val)
        elif name in ("train.health.grad_norm", "train.health.param_norm",
                      "train.health.update_ratio"):
            tails[name[len("train.health."):]] = val
        elif name == "train.health.loss":
            head = dict(labels).get("head", "0")
            tails[f"loss[{head}]"] = val
    per_rule = {}
    for series, val in (counters or {}).items():
        name, labels = _strip_labels(series)
        if name != "train.health.firings":
            continue
        rule = "?"
        for part in labels.split(","):
            if part.startswith("rule="):
                rule = part.split("=", 1)[1].strip('"')
        per_rule[rule] = per_rule.get(rule, 0) + val
    firings = [r for r in records or []
               if r.get("kind") == "train.health"]
    ckpts = [r for r in records or []
             if r.get("kind") == "train.health.ckpt"]
    if state is None and not (per_rule or firings or ckpts):
        return ["training health: plane not armed / no records"]
    tag = _HEALTH_STATES.get(state or 0, str(state))
    head = f"training health: {tag.upper() if state else tag}"
    if per_rule:
        head += " (" + ", ".join(f"{r} x{int(n)}"
                                 for r, n in sorted(per_rule.items())) + ")"
    lines = [head]
    for r in firings[-5:]:
        lines.append(
            f"  epoch {r.get('epoch', '?')} batch {r.get('nbatch', '?')}: "
            f"{r.get('rule', '?')} -> {r.get('policy', '?')} "
            f"(value {r.get('value', '?'):g} vs threshold "
            f"{r.get('threshold', '?'):g})"
            if isinstance(r.get("value"), (int, float)) and
            isinstance(r.get("threshold"), (int, float)) else
            f"  epoch {r.get('epoch', '?')} batch {r.get('nbatch', '?')}: "
            f"{r.get('rule', '?')} -> {r.get('policy', '?')}")
    if tails:
        lines.append("  final series: " + ", ".join(
            f"{k}={v:g}" for k, v in sorted(tails.items())))
    for r in ckpts[-3:]:
        lines.append(f"  emergency checkpoint: seq {r.get('seq', '?')} "
                     f"at epoch {r.get('epoch', '?')} batch "
                     f"{r.get('nbatch', '?')} ({r.get('rule', '?')})")
    return lines


def _slowest_spans(spans, top):
    """Top spans by duration, one line each (op.* and dispatch spans)."""
    interesting = [s for s in spans
                   if s.get("name", "").startswith(("op.", "executor.",
                                                    "kvstore.", "io."))]
    interesting.sort(key=lambda s: s.get("dur_us", 0), reverse=True)
    lines = []
    for s in interesting[:top]:
        lines.append(f"  {_fmt_us(s.get('dur_us', 0)):>10}  "
                     f"{s.get('name', '?')}")
    return ["slowest recorded spans:"] + (lines or ["  (no spans)"])


# ------------------------------------------------------------ crash path
def render_crash(report, top=10):
    """Crash-report dict -> human-readable text."""
    out = ["=" * 64, "CRASH REPORT", "=" * 64]
    out.append(f"time:  {report.get('time', '?')}   "
               f"pid {report.get('pid', '?')}")
    out.append(f"where: {report.get('where') or 'unknown'}")
    exc = report.get("exception")
    if exc:
        out.append(f"error: {exc.get('type', '?')}: "
                   f"{exc.get('message', '')}")
    backend = report.get("backend")
    devs = report.get("devices") or []
    if backend or devs:
        kinds = sorted({d.get("device_kind", "?") for d in devs})
        out.append(f"backend: {backend or '?'} — {len(devs)} device(s) "
                   f"({', '.join(kinds)})")
    out.append("")

    metrics = report.get("metrics") or {}
    out += _jit_cache_section(metrics.get("counters") or {})
    out += _memory_section(report.get("memory"))

    ring = report.get("ring") or []
    anoms = [r for r in ring if r.get("kind") == "anomaly"]
    out += _anomaly_section(anoms)
    out += _train_health_section(
        metrics.get("counters") or {},
        _gauge_triples_from_series(metrics.get("gauges") or {}),
        ring)
    out += _lint_section(metrics.get("counters") or {},
                         [r for r in ring if r.get("kind") == "lint.finding"])
    out += _roofline_section(
        _gauge_triples_from_series(metrics.get("gauges") or {}),
        [r for r in ring if r.get("kind") == "span"], top=top)
    out += _memplan_section(
        _gauge_triples_from_series(metrics.get("gauges") or {}),
        [r for r in ring if r.get("kind") == "memplan.plan"])
    out += _serving_section(
        metrics.get("counters") or {},
        _gauge_triples_from_series(metrics.get("gauges") or {}),
        _hist_entries_from_series(metrics.get("histograms") or {}))
    out += _decode_section(
        metrics.get("counters") or {},
        _gauge_triples_from_series(metrics.get("gauges") or {}),
        _hist_entries_from_series(metrics.get("histograms") or {}))
    out += _checkpoint_section(
        metrics.get("counters") or {},
        _gauge_triples_from_series(metrics.get("gauges") or {}),
        _hist_entries_from_series(metrics.get("histograms") or {}),
        ring)
    out += _faults_section(
        metrics.get("counters") or {},
        _gauge_triples_from_series(metrics.get("gauges") or {}),
        ring)
    out += _traces_section(
        [r for r in ring if r.get("kind") == "trace.span"],
        metrics.get("counters") or {},
        _hist_entries_from_series(metrics.get("histograms") or {}),
        [r for r in ring if r.get("kind") == "step.straggler"])

    # throughput from ring batch records
    batches = [r for r in ring if r.get("kind") == "module.fit.batch"
               or (r.get("kind") == "batch_end")]
    if batches:
        durs = [r.get("dur_us") or r.get("duration_us") for r in batches]
        durs = [d for d in durs if d]
        if durs:
            mean_us = sum(durs) / len(durs)
            out.append(f"recent batches: {len(batches)} in ring, mean "
                       f"{_fmt_us(mean_us)}/batch, last "
                       f"{_fmt_us(durs[-1])}")
    spans = [r for r in ring if r.get("kind") == "span"]
    out += _slowest_spans(spans, top)

    out.append("")
    out.append(f"recent activity (newest last, {len(ring)} ring entries):")
    t_end = ring[-1].get("ts_us", 0) if ring else 0
    for r in ring[-top:]:
        dt = (r.get("ts_us", t_end) - t_end) / 1e6
        desc = {k: v for k, v in r.items() if k not in ("kind", "ts_us")}
        out.append(f"  {dt:+9.3f}s  {r.get('kind', '?'):<20} {desc}")
    env = report.get("env") or {}
    knobs = {k: v for k, v in env.items() if k.startswith("MXNET_")}
    if knobs:
        out.append("")
        out.append("MXNET_* env: " + ", ".join(
            f"{k}={v}" for k, v in sorted(knobs.items())))
    return "\n".join(out)


# ------------------------------------------------------------ jsonl path
def render_jsonl(lines, top=10):
    """Telemetry jsonl lines -> health-report text."""
    events, spans, counters, gauges, hists = [], [], {}, {}, {}
    traces = []                     # trace-plane span records
    hist_entries = []               # (name, labels, rec) — labels kept
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        t = rec.get("type")
        if t == "event":
            events.append(rec)
        elif t == "trace":
            traces.append(rec)
        elif t == "span":
            spans.append(rec)
        elif t == "counter":
            labels = rec.get("labels") or {}
            key = rec.get("name", "?")
            if labels:
                inner = ",".join(f'{k}="{v}"'
                                 for k, v in sorted(labels.items()))
                key = f"{key}{{{inner}}}"
            counters[key] = counters.get(key, 0) + rec.get("value", 0)
        elif t == "gauge":
            gauges[(rec.get("name", "?"),
                    tuple(sorted((rec.get("labels") or {}).items())))] = \
                rec.get("value")
        elif t == "histogram":
            hists[rec.get("name", "?")] = rec
            hist_entries.append((rec.get("name", "?"),
                                 rec.get("labels") or {}, rec))

    out = ["=" * 64, "TELEMETRY HEALTH REPORT", "=" * 64]

    # throughput trend: batch_end durations (or speed events), first vs
    # last third of the run
    speeds = []
    for e in events:
        if e.get("kind") == "speed" and e.get("samples_per_sec"):
            speeds.append(float(e["samples_per_sec"]))
        elif e.get("kind") == "batch_end":
            dur, bs = e.get("duration_us") or 0, e.get("batch_size") or 0
            if dur > 0 and bs > 0:
                speeds.append(bs / (dur / 1e6))
    if speeds:
        third = max(1, len(speeds) // 3)
        head = sum(speeds[:third]) / third
        tail = sum(speeds[-third:]) / len(speeds[-third:])
        trend = (tail / head - 1.0) * 100.0 if head else 0.0
        arrow = "stable" if abs(trend) < 5 else \
            ("IMPROVING" if trend > 0 else "DEGRADING")
        out.append(f"throughput: {sum(speeds) / len(speeds):.1f} "
                   f"samples/s mean over {len(speeds)} batches; trend "
                   f"{trend:+.1f}% (first vs last third) — {arrow}")
    else:
        out.append("throughput: no batch_end/speed events in log")

    out += _jit_cache_section(counters)

    # memory gauges from the registry section
    mem = {}
    for (name, labels), val in gauges.items():
        if name in ("memory.live_bytes", "memory.peak_bytes"):
            ctx = dict(labels).get("ctx", "?")
            slot = "live_bytes" if name.endswith("live_bytes") \
                else "peak_bytes"
            mem.setdefault(ctx, {})[slot] = val
    out += _memory_section(mem)

    anoms = [{"what": e.get("what"), "array": e.get("array"),
              "step": e.get("step"), "trace": e.get("trace"),
              "ts_us": e.get("ts_us", 0)}
             for e in events if e.get("kind") == "anomaly"]
    out += _anomaly_section(anoms)
    out += _train_health_section(
        counters,
        [(name, dict(labels), val)
         for (name, labels), val in gauges.items()],
        [e for e in events
         if e.get("kind") in ("train.health", "train.health.ckpt")])
    out += _lint_section(counters,
                         [e for e in events
                          if e.get("kind") == "lint.finding"])
    out += _roofline_section(
        [(name, dict(labels), val)
         for (name, labels), val in gauges.items()],
        spans, top=top)
    out += _memplan_section(
        [(name, dict(labels), val)
         for (name, labels), val in gauges.items()],
        [e for e in events if e.get("kind") == "memplan.plan"])
    out += _serving_section(
        counters,
        [(name, dict(labels), val)
         for (name, labels), val in gauges.items()],
        hist_entries)
    out += _decode_section(
        counters,
        [(name, dict(labels), val)
         for (name, labels), val in gauges.items()],
        hist_entries)
    out += _checkpoint_section(
        counters,
        [(name, dict(labels), val)
         for (name, labels), val in gauges.items()],
        hist_entries,
        events)
    out += _faults_section(
        counters,
        [(name, dict(labels), val)
         for (name, labels), val in gauges.items()],
        events)
    out += _traces_section(
        traces, counters, hist_entries,
        [e for e in events if e.get("kind") == "step.straggler"])
    out += _slowest_spans(spans, top)

    h = hists.get("module.fit.batch.seconds")
    if h:
        out.append(f"batch time: mean {h.get('mean', 0) * 1e3:.1f} ms, "
                   f"min {h.get('min', 0) * 1e3:.1f} / max "
                   f"{h.get('max', 0) * 1e3:.1f} ms over "
                   f"{h.get('count', 0)} batches")
    return "\n".join(out)


# ------------------------------------------------------------------ entry
def render_file(path, top=10):
    """Auto-detect and render; returns the report text."""
    with open(path) as f:
        text = f.read()
    stripped = text.lstrip()
    if stripped.startswith("{"):
        try:
            doc = json.loads(text)
            if isinstance(doc, dict) and doc.get("type") == "crash_report":
                return render_crash(doc, top=top)
        except json.JSONDecodeError:
            pass                   # multi-object jsonl: fall through
    return render_jsonl(text.splitlines(), top=top)


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Render a flight-recorder crash dump or telemetry "
                    "jsonl log as a human-readable health report.")
    p.add_argument("path", help="mxnet_crash_*.json or *.jsonl file")
    p.add_argument("--top", type=int, default=10,
                   help="how many slowest spans / timeline rows to show")
    args = p.parse_args(argv)
    try:
        print(render_file(args.path, top=args.top))
    except FileNotFoundError:
        print(f"no such file: {args.path}", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
