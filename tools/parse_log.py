#!/usr/bin/env python
"""Extract per-epoch metrics and throughput from training logs.

Reference counterpart: tools/parse_log.py, which the nightly accuracy
gates consume (reference: tests/nightly/test_all.sh:42-55 check_val).
Parses this framework's fit log lines:

    Epoch[3] Train-accuracy=0.913000
    Epoch[3] Time cost=12.345
    Epoch[3] Validation-accuracy=0.887000
    Epoch[3] Batch[40] speed=1234.56 samples/s ...

Also parses the telemetry JSON-lines event log (mxnet_tpu.telemetry.jsonl
— ``{"type": "event", "kind": "epoch_end"|"batch_end"|"speed", ...}`` one
object per line): epoch times/metrics come from ``epoch_end`` records and
throughput from ``batch_end`` durations (or ``speed`` events when a
Speedometer ran). Detection is automatic — a log whose first
non-blank line is a JSON object takes the telemetry path.

Usage:
    python tools/parse_log.py train.log [--format markdown|csv]
    python tools/parse_log.py telemetry.jsonl   (same table, same gates)
    python tools/parse_log.py train.log --check-val accuracy:0.85
        (exit 1 if the final validation metric is below the threshold —
         the nightly gating mode)
"""
from __future__ import annotations

import argparse
import json
import re
import sys
from collections import defaultdict

EPOCH_METRIC = re.compile(
    r"Epoch\[(\d+)\]\s+(Train|Validation)-([\w-]+)=([0-9.eE+-]+)")
EPOCH_TIME = re.compile(r"Epoch\[(\d+)\]\s+Time cost=([0-9.eE+-]+)")
BATCH_SPEED = re.compile(
    r"Epoch\[(\d+)\]\s+Batch\[\d+\]\s+speed=([0-9.eE+-]+)")


def parse(lines):
    """-> {epoch: {"train": {m: v}, "val": {m: v}, "time": s,
                   "speed": mean samples/s}}"""
    out = defaultdict(lambda: {"train": {}, "val": {},
                               "time": None, "_speeds": []})
    for line in lines:
        m = EPOCH_METRIC.search(line)
        if m:
            epoch, which, name, val = m.groups()
            key = "train" if which == "Train" else "val"
            out[int(epoch)][key][name] = float(val)
            continue
        m = EPOCH_TIME.search(line)
        if m:
            out[int(m.group(1))]["time"] = float(m.group(2))
            continue
        m = BATCH_SPEED.search(line)
        if m:
            out[int(m.group(1))]["_speeds"].append(float(m.group(2)))
    for rec in out.values():
        sp = rec.pop("_speeds")
        rec["speed"] = sum(sp) / len(sp) if sp else None
    return dict(out)


def looks_like_telemetry(lines):
    """True when the first non-blank line is a JSON object (the
    telemetry jsonl log); leaves nothing consumed for list inputs."""
    for line in lines:
        line = line.strip()
        if line:
            return line.startswith("{")
    return False


def parse_telemetry(lines):
    """Telemetry jsonl -> the same table shape ``parse`` produces.

    Epoch rows come from ``epoch_end`` events (time cost + train
    metrics). Throughput prefers explicit Speedometer ``speed`` events;
    otherwise it is derived from ``batch_end`` durations as
    batch_size / duration (the batches/sec * batch-size identity).
    """
    out = defaultdict(lambda: {"train": {}, "val": {},
                               "time": None, "_speeds": []})
    derived = defaultdict(list)
    has_speed_events = set()
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if rec.get("type") != "event":
            continue
        kind = rec.get("kind")
        epoch = rec.get("epoch")
        if kind == "epoch_end" and epoch is not None:
            out[int(epoch)]["time"] = rec.get("time_cost_s")
            for name, val in (rec.get("metrics") or {}).items():
                out[int(epoch)]["train"][name] = float(val)
        elif kind == "speed" and epoch is not None:
            out[int(epoch)]["_speeds"].append(float(rec["samples_per_sec"]))
            has_speed_events.add(int(epoch))
        elif kind == "batch_end" and epoch is not None:
            dur_us = rec.get("duration_us") or 0
            bs = rec.get("batch_size") or 0
            if dur_us > 0 and bs > 0:
                derived[int(epoch)].append(bs / (dur_us / 1e6))
    for epoch, speeds in derived.items():
        if epoch not in has_speed_events:
            out[epoch]["_speeds"].extend(speeds)
    for rec in out.values():
        sp = rec.pop("_speeds")
        rec["speed"] = sum(sp) / len(sp) if sp else None
    return dict(out)


def render(table, fmt="markdown"):
    metrics = sorted({m for rec in table.values()
                      for m in list(rec["train"]) + list(rec["val"])})
    cols = ["epoch"] + [f"train-{m}" for m in metrics] + \
        [f"val-{m}" for m in metrics] + ["time(s)", "samples/s"]
    rows = []
    for epoch in sorted(table):
        rec = table[epoch]
        row = [str(epoch)]
        row += [f"{rec['train'].get(m, ''):.6f}"
                if m in rec["train"] else "" for m in metrics]
        row += [f"{rec['val'].get(m, ''):.6f}"
                if m in rec["val"] else "" for m in metrics]
        row.append(f"{rec['time']:.1f}" if rec["time"] is not None else "")
        row.append(f"{rec['speed']:.1f}" if rec["speed"] is not None else "")
        rows.append(row)
    if fmt == "csv":
        return "\n".join(",".join(r) for r in [cols] + rows)
    widths = [max(len(c), *(len(r[i]) for r in rows)) if rows else len(c)
              for i, c in enumerate(cols)]
    lines = [" | ".join(c.ljust(w) for c, w in zip(cols, widths)),
             "-|-".join("-" * w for w in widths)]
    lines += [" | ".join(c.ljust(w) for c, w in zip(r, widths))
              for r in rows]
    return "\n".join(lines)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("logfile")
    p.add_argument("--format", choices=("markdown", "csv"),
                   default="markdown")
    p.add_argument("--check-val", metavar="METRIC:THRESHOLD",
                   help="exit nonzero unless the last epoch's validation "
                        "METRIC >= THRESHOLD (nightly gate mode)")
    args = p.parse_args()
    with open(args.logfile) as f:
        lines = f.readlines()
    table = parse_telemetry(lines) if looks_like_telemetry(lines) \
        else parse(lines)
    if not table:
        print("no epochs found", file=sys.stderr)
        return 2
    print(render(table, args.format))
    if args.check_val:
        name, thresh = args.check_val.split(":")
        last = table[max(table)]
        val = last["val"].get(name)
        if val is None:
            print(f"check-val: no validation metric {name!r}",
                  file=sys.stderr)
            return 2
        if val < float(thresh):
            print(f"check-val FAILED: {name}={val} < {thresh}",
                  file=sys.stderr)
            return 1
        print(f"check-val ok: {name}={val} >= {thresh}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
