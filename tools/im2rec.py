#!/usr/bin/env python
"""im2rec: pack an image dataset into RecordIO (reference: tools/im2rec.py
+ tools/im2rec.cc — same .lst / .rec / .idx formats).

Two modes, like the reference:

  list generation (one class per sub-directory of root):
      python tools/im2rec.py --list prefix root

  packing (reads prefix.lst, writes prefix.rec + prefix.idx):
      python tools/im2rec.py prefix root [--resize N] [--quality Q]
                                          [--num-thread T]

.lst rows are "index\\tlabel(s...)\\trelative_path"; records are packed
with IRHeader(label) + JPEG bytes, readable by ImageIter /
ImageRecordIter / ImageDetIter.
"""
from __future__ import annotations

import argparse
import os
import sys
from concurrent.futures import ThreadPoolExecutor

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import numpy as np  # noqa: E402

from mxnet_tpu import recordio  # noqa: E402
from mxnet_tpu.image import _imdecode_np, _resize_short_np  # noqa: E402

EXTS = (".jpg", ".jpeg", ".png", ".bmp")


def make_list(prefix, root, recursive=True):
    classes = sorted(
        d for d in os.listdir(root)
        if os.path.isdir(os.path.join(root, d))) if recursive else []
    rows = []
    if classes:
        for lab, cls in enumerate(classes):
            cdir = os.path.join(root, cls)
            for fn in sorted(os.listdir(cdir)):
                if fn.lower().endswith(EXTS):
                    rows.append((float(lab), os.path.join(cls, fn)))
    else:
        for fn in sorted(os.listdir(root)):
            if fn.lower().endswith(EXTS):
                rows.append((0.0, fn))
    lst = prefix + ".lst"
    with open(lst, "w") as f:
        for i, (lab, path) in enumerate(rows):
            f.write(f"{i}\t{lab}\t{path}\n")
    print(f"wrote {lst}: {len(rows)} images, "
          f"{len(classes)} classes")
    return lst


def read_list(path):
    with open(path) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            idx = int(parts[0])
            labels = [float(x) for x in parts[1:-1]]
            yield idx, labels, parts[-1]


def _encode(img, quality, img_fmt=".jpg"):
    try:
        import cv2
        ok, buf = cv2.imencode(img_fmt, img[:, :, ::-1],
                               [cv2.IMWRITE_JPEG_QUALITY, quality])
        if not ok:
            raise RuntimeError("imencode failed")
        return buf.tobytes()
    except ImportError:
        import io as _io
        from PIL import Image
        bio = _io.BytesIO()
        Image.fromarray(img).save(bio, format="JPEG", quality=quality)
        return bio.getvalue()


def _load(path, resize):
    with open(path, "rb") as f:
        img = _imdecode_np(f.read()).astype(np.uint8)
    if resize:
        img = np.asarray(_resize_short_np(img, resize), dtype=np.uint8)
    return img


def pack(prefix, root, resize=0, quality=95, num_thread=4):
    lst = prefix + ".lst"
    if not os.path.exists(lst):
        raise SystemExit(f"{lst} not found — run --list first")
    items = list(read_list(lst))
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")

    def job(item):
        idx, labels, path = item
        img = _load(os.path.join(root, path), resize)
        buf = _encode(img, quality)
        label = labels[0] if len(labels) == 1 else np.asarray(
            labels, dtype=np.float32)
        header = recordio.IRHeader(0, label, idx, 0)
        return idx, recordio.pack(header, buf)

    n = 0
    with ThreadPoolExecutor(num_thread) as pool:
        for idx, packed in pool.map(job, items):
            rec.write_idx(idx, packed)
            n += 1
            if n % 1000 == 0:
                print(f"packed {n}/{len(items)}")
    rec.close()
    print(f"wrote {prefix}.rec / {prefix}.idx: {n} records")


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("prefix")
    p.add_argument("root")
    p.add_argument("--list", action="store_true",
                   help="generate prefix.lst from root instead of packing")
    p.add_argument("--resize", type=int, default=0,
                   help="resize shorter edge before packing")
    p.add_argument("--quality", type=int, default=95)
    p.add_argument("--num-thread", type=int, default=4)
    args = p.parse_args()
    if args.list:
        make_list(args.prefix, args.root)
    else:
        pack(args.prefix, args.root, args.resize, args.quality,
             args.num_thread)


if __name__ == "__main__":
    main()
