#!/usr/bin/env python
"""Measure KVStore push/pull bandwidth (reference: tools/bandwidth/ —
"measures the communication bandwidth per batch", docs perf.md:197-199).

Simulates one Module.update round: push a gradient set, pull the weights
back, repeat; reports effective GB/s over the payload. Works for local
stores and, under tools/launch.py, for dist_sync (where push is the
bucketed all-reduce over the coordination runtime).

    python tools/bandwidth.py --size-mb 64 --num-keys 16 --repeat 10
    python tools/launch.py -n 4 python tools/bandwidth.py --kv-store dist_sync
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--kv-store", default="local")
    p.add_argument("--size-mb", type=float, default=64.0,
                   help="total payload per round")
    p.add_argument("--num-keys", type=int, default=16)
    p.add_argument("--repeat", type=int, default=10)
    p.add_argument("--cpu", action="store_true",
                   help="force the CPU backend (for launch.py runs)")
    args = p.parse_args()
    if args.cpu or int(os.environ.get("DMLC_NUM_WORKER", "1")) > 1:
        import jax
        jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import mxnet_tpu as mx

    kv = mx.kv.create(args.kv_store)
    n_per_key = max(int(args.size_mb * (1 << 20) / 4 / args.num_keys), 1)
    keys = list(range(args.num_keys))
    vals = [mx.nd.ones((n_per_key,)) for _ in keys]
    outs = [mx.nd.empty((n_per_key,)) for _ in keys]
    def sync():
        # force EVERY key's transfer to complete — async dispatch would
        # otherwise leave keys in flight outside the timed window
        for o in outs:
            o.asnumpy()

    kv.init(keys, vals)
    kv.push(keys, vals)            # warm (compile collectives)
    kv.pull(keys, out=outs)
    sync()
    payload = args.num_keys * n_per_key * 4 / (1 << 30)

    tic = time.perf_counter()
    for _ in range(args.repeat):
        kv.push(keys, vals)
        kv.pull(keys, out=outs)
    sync()
    toc = time.perf_counter()
    per_round = (toc - tic) / args.repeat
    print(json.dumps({
        "metric": "kvstore_push_pull_bandwidth",
        "kv_store": kv.type,
        "rank": kv.rank,
        "num_workers": kv.num_workers,
        "payload_gb": round(payload, 4),
        "seconds_per_round": round(per_round, 4),
        "gb_per_sec": round(2 * payload / per_round, 3),   # push + pull
    }), flush=True)


if __name__ == "__main__":
    main()
