#!/usr/bin/env python
"""mxlint: lint symbol JSON files and bundled models for graph hazards.

The CLI face of ``mxnet_tpu.analysis`` — the same five static-analysis
passes that run at ``bind(validate=...)`` time (graph verifier,
donation/aliasing, collective order, retrace churn, host sync), pointed
at artifacts instead of live bindings:

* a saved symbol JSON (``model-symbol.json``) — structural rules
  (dangling inputs, dead nodes) plus the full pass set over the loaded
  graph, optionally seeded with ``--shape name=1,3,224,224``;
* ``--check`` — the CI gate: lints every bundled ``mxnet_tpu/models/``
  symbol and the two ``examples/dcgan.py`` graphs under their canonical
  input shapes, expecting zero findings.

Exit status: 0 = no error-severity findings (``--strict``: no findings
at all), 1 = findings at the failing severity, 2 = usage/IO trouble.
Suppress rules with ``MXNET_LINT_DISABLE=GV107,HS501,...``.

Usage:
    python tools/mxlint.py model-symbol.json --shape data=1,3,224,224
    python tools/mxlint.py --check
    python tools/mxlint.py --rules
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def _parse_shape_args(pairs):
    shapes = {}
    for pair in pairs or []:
        if "=" not in pair:
            raise ValueError(f"--shape expects name=d0,d1,..., got {pair!r}")
        name, _, dims = pair.partition("=")
        dims = dims.strip("() ")
        shapes[name.strip()] = tuple(
            int(d) for d in dims.replace(" ", "").split(",") if d)
    return shapes


# The CI gate's corpus: every bundled model plus the two example graphs,
# each under its canonical input shapes (kept small — lint never runs
# the graphs, it only infers over them).
def _check_corpus():
    from mxnet_tpu import models as _models

    corpus = [
        ("models/mlp", lambda: _models.mlp.get_symbol(10),
         {"data": (8, 784)}),
        ("models/lenet", lambda: _models.lenet.get_symbol(10),
         {"data": (8, 1, 28, 28)}),
        ("models/alexnet", lambda: _models.alexnet.get_symbol(10),
         {"data": (2, 3, 224, 224)}),
        ("models/vgg16", lambda: _models.vgg.get_symbol(10, 16),
         {"data": (1, 3, 224, 224)}),
        ("models/resnet20", lambda: _models.resnet.get_symbol(
            10, 20, "3,32,32"), {"data": (4, 3, 32, 32)}),
        ("models/inception_bn", lambda: _models.inception_bn.get_symbol(10),
         {"data": (1, 3, 224, 224)}),
        ("models/inception_v3", lambda: _models.inception_v3.get_symbol(10),
         {"data": (1, 3, 299, 299)}),
    ]

    def _dcgan(which):
        examples_dir = os.path.join(_REPO_ROOT, "examples")
        if examples_dir not in sys.path:
            sys.path.insert(0, examples_dir)
        import dcgan
        if which == "generator":
            return dcgan.make_generator()
        return dcgan.make_discriminator()

    corpus.append(("examples/dcgan.generator",
                   lambda: _dcgan("generator"), {"rand": (2, 64, 1, 1)}))
    corpus.append(("examples/dcgan.discriminator",
                   lambda: _dcgan("discriminator"),
                   {"data": (2, 3, 32, 32), "label": (2, 1)}))
    return corpus


def run_check(out, as_json=False):
    """Lint the bundled corpus; returns the merged findings list."""
    from mxnet_tpu import analysis

    findings = []
    for name, build, shapes in _check_corpus():
        try:
            report = analysis.lint_symbol(build(), shapes=shapes)
        except Exception as e:  # noqa: BLE001 — a crashing build is a failure
            findings.append({"target": name, "rule": "XX001",
                             "severity": "error",
                             "message": f"could not build/lint: "
                                        f"{type(e).__name__}: {e}"})
            continue
        for d in report:
            rec = d.as_dict()
            rec["target"] = name
            findings.append(rec)
        if not as_json:
            status = "ok" if not len(report) else \
                f"{len(report)} finding(s)"
            print(f"  {name:<32} {status}", file=out)
    return findings


def lint_path(path, shapes, out, as_json=False):
    """Lint one symbol JSON file; returns the findings list."""
    from mxnet_tpu import analysis

    with open(path) as f:
        text = f.read()
    report = analysis.lint_json(text, shapes=shapes or None)
    findings = []
    for d in report:
        rec = d.as_dict()
        rec["target"] = path
        findings.append(rec)
    if not as_json:
        status = "ok" if not len(report) else f"{len(report)} finding(s)"
        print(f"  {path:<32} {status}", file=out)
    return findings


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="mxlint",
        description="Static graph verifier & hazard linter "
                    "(mxnet_tpu.analysis) over symbol JSON files and the "
                    "bundled model zoo.")
    p.add_argument("paths", nargs="*",
                   help="symbol JSON files (e.g. model-symbol.json)")
    p.add_argument("--check", action="store_true",
                   help="lint the bundled models + example graphs "
                        "(the CI gate)")
    p.add_argument("--shape", action="append", metavar="NAME=D0,D1,...",
                   help="seed an input shape for inference "
                        "(repeatable)")
    p.add_argument("--rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("--mfu-audit", action="store_true", dest="mfu_audit",
                   help="list registry ops missing flops/bytes cost "
                        "metadata (MFU coverage gaps; rule MF601) and "
                        "exit")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit findings as one JSON document")
    p.add_argument("--strict", action="store_true",
                   help="exit nonzero on ANY finding (default: errors "
                        "only)")
    args = p.parse_args(argv)
    out = sys.stdout

    if args.rules:
        from mxnet_tpu.analysis import RULES
        for rule in sorted(RULES):
            sev, title = RULES[rule]
            print(f"{rule}  [{sev:<7}] {title}", file=out)
        return 0

    if args.mfu_audit:
        # registry-wide coverage audit (MF601's graph-level cousin):
        # every op here is invisible to MFU/roofline accounting
        from mxnet_tpu.ops.cost import uncovered_ops
        from mxnet_tpu.ops.registry import OP_REGISTRY
        missing = uncovered_ops()
        covered = len({id(o) for o in OP_REGISTRY.values()}) - len(missing)
        if args.as_json:
            json.dump({"covered_ops": covered,
                       "uncovered_ops": missing}, out, indent=2)
            print(file=out)
        else:
            for name in missing:
                print(f"  MF601 [info] op {name!r} has no flops/bytes "
                      "cost metadata", file=out)
            print(f"mxlint: {covered} ops covered, {len(missing)} "
                  "missing cost metadata (seed ops/cost.py)", file=out)
        return 0

    if not args.check and not args.paths:
        p.print_usage(file=sys.stderr)
        print("mxlint: nothing to lint (pass symbol JSON paths or "
              "--check)", file=sys.stderr)
        return 2

    try:
        shapes = _parse_shape_args(args.shape)
    except ValueError as e:
        print(f"mxlint: {e}", file=sys.stderr)
        return 2

    findings = []
    try:
        if args.check:
            findings += run_check(out, as_json=args.as_json)
        for path in args.paths:
            findings += lint_path(path, shapes, out, as_json=args.as_json)
    except FileNotFoundError as e:
        print(f"mxlint: {e}", file=sys.stderr)
        return 2

    errors = [f for f in findings if f["severity"] == "error"]
    if args.as_json:
        json.dump({"findings": findings, "errors": len(errors)}, out,
                  indent=2)
        print(file=out)
    else:
        for f in findings:
            where = f" at node '{f['node']}'" if f.get("node") else ""
            print(f"{f['target']}: {f['rule']} [{f['severity']}]"
                  f"{where}: {f['message']}", file=out)
            if f.get("hint"):
                print(f"    hint: {f['hint']}", file=out)
        print(f"mxlint: {len(findings)} finding(s), {len(errors)} "
              f"error(s)", file=out)

    if errors or (args.strict and findings):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
