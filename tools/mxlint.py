#!/usr/bin/env python
"""mxlint: lint symbol JSON files and bundled models for graph hazards.

The CLI face of ``mxnet_tpu.analysis`` — the same static-analysis
passes that run at ``bind(validate=...)`` time (graph verifier,
donation/aliasing, collective order, retrace churn, host sync,
precision flow), pointed at artifacts instead of live bindings:

* a saved symbol JSON (``model-symbol.json``) — structural rules
  (dangling inputs, dead nodes) plus the full pass set over the loaded
  graph, optionally seeded with ``--shape name=1,3,224,224``;
* ``--check`` — the CI gate: lints every bundled ``mxnet_tpu/models/``
  symbol and the two ``examples/dcgan.py`` graphs under their canonical
  input shapes (expecting zero findings), runs the precision audit over
  the bundled models at bf16 AND int8-quantized tiers, plans resnet20's
  memory at two remat policies, and runs the env-var and metric-name
  doc-sync audits;
* ``--precision-audit`` — the QT7xx precision-flow pass alone over the
  bundled models, at f32 and simulated-bf16 compute plus the int8
  quant-rewritten variants (``--compute-dtype`` overrides);
* ``--memory-plan <model>`` — the static memory planner: peak-HBM
  components for one bundled model with ``--policy`` (repeatable),
  ``--batch``, ``--num-devices``/``--zero``, ``--optimizer``; ME801/802
  findings against ``--capacity-gb`` (default: the current device's
  HBM table entry, when known);
* ``--env-audit`` — MXNET_* env reads vs docs/env_var.md rows, both
  directions (the CI doc-sync gate);
* ``--metric-audit`` — recorded metric names vs the docs/telemetry.md
  Metric catalog, both directions (the registry's doc-sync gate);
* ``--mfu-audit`` — registry cost-metadata coverage, plus the memory
  planner's per-op byte sizes over resnet20 (the shared byte table the
  roofline and the planner both consume).

Exit status: 0 = no error-severity findings (``--strict``: no findings
at all), 1 = findings at the failing severity (or audit drift), 2 =
usage/IO trouble. Suppress rules with
``MXNET_LINT_DISABLE=GV107,HS501,...``.

Usage:
    python tools/mxlint.py model-symbol.json --shape data=1,3,224,224
    python tools/mxlint.py --check
    python tools/mxlint.py --rules
    python tools/mxlint.py --precision-audit
    python tools/mxlint.py --memory-plan resnet20 --policy dots --batch 256
    python tools/mxlint.py --env-audit
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def _parse_shape_args(pairs):
    shapes = {}
    for pair in pairs or []:
        if "=" not in pair:
            raise ValueError(f"--shape expects name=d0,d1,..., got {pair!r}")
        name, _, dims = pair.partition("=")
        dims = dims.strip("() ")
        shapes[name.strip()] = tuple(
            int(d) for d in dims.replace(" ", "").split(",") if d)
    return shapes


# The CI gate's corpus: every bundled model plus the two example graphs,
# each under its canonical input shapes (kept small — lint never runs
# the graphs, it only infers over them).
def _check_corpus():
    from mxnet_tpu import models as _models

    corpus = [
        ("models/mlp", lambda: _models.mlp.get_symbol(10),
         {"data": (8, 784)}),
        ("models/lenet", lambda: _models.lenet.get_symbol(10),
         {"data": (8, 1, 28, 28)}),
        ("models/alexnet", lambda: _models.alexnet.get_symbol(10),
         {"data": (2, 3, 224, 224)}),
        ("models/vgg16", lambda: _models.vgg.get_symbol(10, 16),
         {"data": (1, 3, 224, 224)}),
        ("models/resnet20", lambda: _models.resnet.get_symbol(
            10, 20, "3,32,32"), {"data": (4, 3, 32, 32)}),
        ("models/inception_bn", lambda: _models.inception_bn.get_symbol(10),
         {"data": (1, 3, 224, 224)}),
        ("models/inception_v3", lambda: _models.inception_v3.get_symbol(10),
         {"data": (1, 3, 299, 299)}),
        ("models/transformer", lambda: _models.transformer.get_symbol(
            vocab_size=64, d_model=32, n_layer=1, n_head=2, seq_len=8),
         {"data": (4, 8)}),
        ("models/transformer_decode",
         lambda: _models.transformer.get_decode_symbol(
             vocab_size=64, d_model=32, n_layer=1, n_head=2, capacity=16),
         {"data": (4, 1)}),
        ("models/transformer_decode_slots",
         lambda: _models.transformer.get_decode_symbol(
             vocab_size=64, d_model=32, n_layer=1, n_head=2, capacity=16,
             per_slot=True), {"data": (4, 1)}),
        # chunked-prefill window graph (S>1 per-slot decode) and the
        # draft/verify pair's verify window — the decode fast paths'
        # serving graphs (serve/decode.py)
        ("models/transformer_decode_chunked",
         lambda: _models.transformer.get_decode_symbol(
             vocab_size=64, d_model=32, n_layer=1, n_head=2, capacity=16,
             per_slot=True, step_len=8), {"data": (4, 8)}),
        ("models/transformer_decode_verify",
         lambda: _models.transformer.get_decode_symbol(
             vocab_size=64, d_model=32, n_layer=1, n_head=2, capacity=16,
             per_slot=True, step_len=4), {"data": (4, 4)}),
    ]

    def _dcgan(which):
        examples_dir = os.path.join(_REPO_ROOT, "examples")
        if examples_dir not in sys.path:
            sys.path.insert(0, examples_dir)
        import dcgan
        if which == "generator":
            return dcgan.make_generator()
        return dcgan.make_discriminator()

    corpus.append(("examples/dcgan.generator",
                   lambda: _dcgan("generator"), {"rand": (2, 64, 1, 1)}))
    corpus.append(("examples/dcgan.discriminator",
                   lambda: _dcgan("discriminator"),
                   {"data": (2, 3, 32, 32), "label": (2, 1)}))
    return corpus


def _model_by_name(name):
    """(build, canonical_shapes) for one bundled-model short name."""
    for target, build, shapes in _check_corpus():
        if target.split("/", 1)[-1] == name or target == name:
            return build, shapes
    raise KeyError(name)


def _with_batch(shapes, batch):
    if not batch:
        return dict(shapes)
    return {nm: (batch,) + tuple(s[1:]) for nm, s in shapes.items()}


def _quantized(build, shapes, dtype="int8"):
    """Quant-rewrite of one corpus model (int8 or fp8 storage)."""
    import numpy as np
    from mxnet_tpu.ndarray import NDArray
    from mxnet_tpu.ops.quant import quantize_symbol
    import jax.numpy as jnp
    sym = build()
    arg_shapes, _o, _a = sym.infer_shape(**shapes)
    # zero weights quantize on the scale-1.0 path — the rewrite and the
    # lint surface are shape/dtype-driven, so cheap params suffice even
    # for the vgg16-sized corpus entries
    args = {nm: NDArray(jnp.zeros(s, np.float32))
            for nm, s in zip(sym.list_arguments(), arg_shapes)
            if nm not in shapes}
    return quantize_symbol(sym, args, dtype=dtype)[0]


def run_precision_audit(out, compute_dtypes=("float32", "bfloat16"),
                        as_json=False, quiet=False):
    """QT7xx pass over the bundled models per compute tier, plus the
    int8 and fp8 quant-rewritten variants; returns the findings list."""
    from mxnet_tpu import analysis

    findings = []
    for name, build, shapes in _check_corpus():
        variants = [(f"{name}@{cd}", lambda b=build: b(), cd)
                    for cd in compute_dtypes]
        if name.startswith("models/"):
            variants.append((f"{name}@int8",
                             lambda b=build, s=shapes: _quantized(b, s),
                             None))
            variants.append((f"{name}@fp8",
                             lambda b=build, s=shapes: _quantized(
                                 b, s, dtype="fp8"),
                             None))
        for target, make, cd in variants:
            try:
                report = analysis.run_passes(analysis.AnalysisContext(
                    symbol=make(), known_shapes=shapes,
                    compute_dtype=cd), passes=["precision_flow"])
            except Exception as e:  # noqa: BLE001
                findings.append({"target": target, "rule": "XX001",
                                 "severity": "error", "node": None,
                                 "hint": None,
                                 "message": f"could not build/audit: "
                                            f"{type(e).__name__}: {e}"})
                continue
            for d in report:
                rec = d.as_dict()
                rec["target"] = target
                findings.append(rec)
            if not as_json and not quiet:
                status = "ok" if not len(report) else \
                    f"{len(report)} finding(s)"
                print(f"  {target:<40} {status}", file=out)
    return findings


def run_memory_plan(model, out, policies=("none",), batch=None,
                    capacity_gb=None, optimizer="sgd_mom", n_data=1,
                    zero=False, as_json=False, quiet=False):
    """Plan one bundled model's memory per policy; ME8xx findings."""
    from mxnet_tpu.analysis import memplan
    from mxnet_tpu.telemetry.mfu import device_hbm_bytes

    build, shapes = _model_by_name(model)
    shapes = _with_batch(shapes, batch)
    capacity = int(capacity_gb * (1 << 30)) if capacity_gb else \
        device_hbm_bytes()
    buckets = (32, 64, 128, 256, 512)
    findings = []
    plans = {}
    for policy in policies:
        plan = memplan.plan_symbol(build(), shapes, policy=policy,
                                   optimizer=optimizer, n_data=n_data,
                                   zero=zero)
        memplan.record_plan(plan, model=model)
        plans[policy] = plan
        for d in memplan.plan_findings(plan, capacity_bytes=capacity,
                                       buckets=buckets, where=model):
            rec = d.as_dict()
            rec["target"] = f"{model}@{policy}"
            findings.append(rec)
        if not as_json and not quiet:
            print(memplan.format_plan(plan, model=model,
                                      capacity_bytes=capacity),
                  file=out)
    if as_json:
        json.dump({"model": model, "plans": plans,
                   "findings": findings}, out, indent=2)
        print(file=out)
    return findings


def run_env_audit(out, as_json=False, quiet=False):
    """Doc-sync audit; returns error-severity findings on drift."""
    from mxnet_tpu.analysis import envaudit

    result = envaudit.audit(_REPO_ROOT)
    findings = []
    for name in result["undocumented"]:
        findings.append({"target": "env-audit", "rule": "XX001",
                         "severity": "error", "node": name,
                         "hint": "add a docs/env_var.md row",
                         "message": f"{name} is read by mxnet_tpu/ but "
                                    "has no docs/env_var.md row"})
    for name in result["dead"]:
        findings.append({"target": "env-audit", "rule": "XX001",
                         "severity": "error", "node": name,
                         "hint": "drop the dead row (or wire the knob)",
                         "message": f"{name} is documented in "
                                    "docs/env_var.md but nothing in "
                                    "mxnet_tpu/ reads it"})
    if as_json:
        json.dump(result, out, indent=2)
        print(file=out)
    elif not quiet:
        print(f"  env-audit: {len(result['code_vars'])} vars read, "
              f"{len(result['doc_vars'])} documented, "
              f"{len(result['undocumented'])} undocumented, "
              f"{len(result['dead'])} dead rows", file=out)
    return findings


def run_metric_audit(out, as_json=False, quiet=False):
    """Metric-name doc-sync audit; error findings on drift."""
    from mxnet_tpu.analysis import metricaudit

    result = metricaudit.audit(_REPO_ROOT)
    findings = []
    for name in result["undocumented"]:
        findings.append({"target": "metric-audit", "rule": "XX001",
                         "severity": "error", "node": name,
                         "hint": "add a docs/telemetry.md catalog row",
                         "message": f"{name} is recorded by mxnet_tpu/ "
                                    "but has no docs/telemetry.md "
                                    "Metric catalog row"})
    for name in result["dead"]:
        findings.append({"target": "metric-audit", "rule": "XX001",
                         "severity": "error", "node": name,
                         "hint": "drop the dead row (or record the "
                                 "metric)",
                         "message": f"{name} is catalogued in "
                                    "docs/telemetry.md but nothing in "
                                    "mxnet_tpu/ records it"})
    if as_json:
        json.dump(result, out, indent=2)
        print(file=out)
    elif not quiet:
        print(f"  metric-audit: {len(result['code_names'])} metrics + "
              f"{len(result['code_prefixes'])} families recorded, "
              f"{len(result['doc_names'])} catalogued, "
              f"{len(result['undocumented'])} undocumented, "
              f"{len(result['dead'])} dead rows", file=out)
    return findings


def run_race_audit(out, as_json=False, quiet=False):
    """RC2xx host-concurrency lint over serve/checkpoint/telemetry/
    faults; returns the findings (error severity, so the CI gate
    enforces zero unannotated)."""
    from mxnet_tpu.analysis import racecheck

    result = racecheck.audit(_REPO_ROOT)
    if as_json:
        json.dump(result, out, indent=2)
        print(file=out)
    elif not quiet:
        print(f"  race-audit: {result['files_scanned']} files, "
              f"{len(result['findings'])} finding(s), "
              f"{len(result['annotated'])} guarded-by annotation(s)",
              file=out)
    return result["findings"]


def run_cachekey_audit(out, as_json=False, quiet=False):
    """CK3xx program-cache-key completeness verifier; returns the
    findings."""
    from mxnet_tpu.analysis import cachekey

    result = cachekey.audit(_REPO_ROOT)
    if as_json:
        json.dump(result, out, indent=2)
        print(file=out)
    elif not quiet:
        covered = sum(1 for v in result["coverage"].values() if v)
        print(f"  cachekey-audit: {len(result['scopes'])} key "
              f"construction scope(s), {covered}/"
              f"{len(result['coverage'])} registered knobs covered, "
              f"{len(result['findings'])} finding(s)", file=out)
    return result["findings"]


def run_determinism_audit(out, as_json=False, quiet=False):
    """DT4xx determinism/replay audit; returns the findings."""
    from mxnet_tpu.analysis import determinism

    result = determinism.audit(_REPO_ROOT)
    if as_json:
        json.dump(result, out, indent=2)
        print(file=out)
    elif not quiet:
        print(f"  determinism-audit: {result['files_scanned']} files, "
              f"{len(result['findings'])} finding(s), "
              f"{len(result['allowed'])} allow annotation(s)", file=out)
    return result["findings"]


def run_check(out, as_json=False):
    """Lint the bundled corpus; returns the merged findings list."""
    from mxnet_tpu import analysis

    findings = []
    for name, build, shapes in _check_corpus():
        try:
            report = analysis.lint_symbol(build(), shapes=shapes)
        except Exception as e:  # noqa: BLE001 — a crashing build is a failure
            findings.append({"target": name, "rule": "XX001",
                             "severity": "error",
                             "message": f"could not build/lint: "
                                        f"{type(e).__name__}: {e}"})
            continue
        for d in report:
            rec = d.as_dict()
            rec["target"] = name
            findings.append(rec)
        if not as_json:
            status = "ok" if not len(report) else \
                f"{len(report)} finding(s)"
            print(f"  {name:<32} {status}", file=out)
    return findings


def lint_path(path, shapes, out, as_json=False):
    """Lint one symbol JSON file; returns the findings list."""
    from mxnet_tpu import analysis

    with open(path) as f:
        text = f.read()
    report = analysis.lint_json(text, shapes=shapes or None)
    findings = []
    for d in report:
        rec = d.as_dict()
        rec["target"] = path
        findings.append(rec)
    if not as_json:
        status = "ok" if not len(report) else f"{len(report)} finding(s)"
        print(f"  {path:<32} {status}", file=out)
    return findings


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="mxlint",
        description="Static graph verifier & hazard linter "
                    "(mxnet_tpu.analysis) over symbol JSON files and the "
                    "bundled model zoo.")
    p.add_argument("paths", nargs="*",
                   help="symbol JSON files (e.g. model-symbol.json)")
    p.add_argument("--check", action="store_true",
                   help="lint the bundled models + example graphs "
                        "(the CI gate)")
    p.add_argument("--shape", action="append", metavar="NAME=D0,D1,...",
                   help="seed an input shape for inference "
                        "(repeatable)")
    p.add_argument("--rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("--mfu-audit", action="store_true", dest="mfu_audit",
                   help="list registry ops missing flops/bytes cost "
                        "metadata (MFU coverage gaps; rule MF601) plus "
                        "the planner's per-op byte sizes, and exit")
    p.add_argument("--precision-audit", action="store_true",
                   dest="precision_audit",
                   help="run the QT7xx precision-flow pass over the "
                        "bundled models (f32 + bf16 + int8-quantized)")
    p.add_argument("--compute-dtype", dest="compute_dtype", default=None,
                   help="compute dtype(s) for --precision-audit, comma-"
                        "separated (default: float32,bfloat16)")
    p.add_argument("--memory-plan", dest="memory_plan", metavar="MODEL",
                   help="static peak-HBM plan for one bundled model "
                        "(e.g. resnet20); ME801/802 findings against "
                        "--capacity-gb")
    p.add_argument("--policy", action="append", dest="policies",
                   choices=["none", "dots", "all"],
                   help="remat policy for --memory-plan (repeatable; "
                        "default none)")
    p.add_argument("--batch", type=int, default=None,
                   help="batch size override for --memory-plan")
    p.add_argument("--capacity-gb", type=float, dest="capacity_gb",
                   default=None,
                   help="device HBM capacity for ME801/802 (default: "
                        "the current device's table entry, if known)")
    p.add_argument("--optimizer", default="sgd_mom",
                   help="optimizer for --memory-plan state sizing "
                        "(default sgd_mom)")
    p.add_argument("--num-devices", type=int, dest="num_devices",
                   default=1,
                   help="data-parallel shard count for --memory-plan")
    p.add_argument("--zero", action="store_true",
                   help="ZeRO-1 state sharding for --memory-plan")
    p.add_argument("--env-audit", action="store_true", dest="env_audit",
                   help="audit MXNET_* env reads against "
                        "docs/env_var.md (both directions)")
    p.add_argument("--metric-audit", action="store_true",
                   dest="metric_audit",
                   help="audit recorded metric names against the "
                        "docs/telemetry.md Metric catalog (both "
                        "directions)")
    p.add_argument("--race-audit", action="store_true",
                   dest="race_audit",
                   help="RC2xx host-concurrency lint over serve/, "
                        "checkpoint/, telemetry/, faults/ (cross-thread "
                        "shared state without a common guard)")
    p.add_argument("--cachekey-audit", action="store_true",
                   dest="cachekey_audit",
                   help="CK3xx program-cache-key completeness: the "
                        "declared knob registry vs. the actual key "
                        "composition")
    p.add_argument("--determinism-audit", action="store_true",
                   dest="determinism_audit",
                   help="DT4xx determinism/replay audit: wall-clock off "
                        "the injectable seam, global RNG draws, "
                        "unordered set iteration")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit findings as one JSON document")
    p.add_argument("--strict", action="store_true",
                   help="exit nonzero on ANY finding (default: errors "
                        "only)")
    args = p.parse_args(argv)
    out = sys.stdout

    if args.rules:
        from mxnet_tpu.analysis import RULES
        for rule in sorted(RULES):
            sev, title = RULES[rule]
            print(f"{rule}  [{sev:<7}] {title}", file=out)
        return 0

    if args.mfu_audit:
        # registry-wide coverage audit (MF601's graph-level cousin):
        # every op here is invisible to MFU/roofline accounting
        from mxnet_tpu.ops.cost import uncovered_ops, partial_cost_ops
        from mxnet_tpu.ops.registry import OP_REGISTRY
        missing = uncovered_ops()
        partial = partial_cost_ops()
        covered = len({id(o) for o in OP_REGISTRY.values()}) - len(missing)
        # the planner's per-op byte sizes over the resnet20 reference
        # graph: the byte table the roofline AND the memory planner
        # consume, surfaced side by side with the coverage gaps
        from mxnet_tpu.analysis import memplan
        build, shapes = _model_by_name("resnet20")
        plan = memplan.plan_symbol(build(), shapes, policy="none")
        planner_bytes = dict(sorted(plan["per_op_bytes"].items(),
                                    key=lambda kv: -kv[1]))
        if args.as_json:
            json.dump({"covered_ops": covered,
                       "uncovered_ops": missing,
                       "partial_cost_ops": partial,
                       "planner_op_bytes": planner_bytes}, out, indent=2)
            print(file=out)
        else:
            for name in missing:
                print(f"  MF601 [info] op {name!r} has no flops/bytes "
                      "cost metadata", file=out)
            for name in partial:
                print(f"  MF601 [warning] op {name!r} has only one of "
                      "flops/bytes (half-seeded estimator)", file=out)
            print("  planner per-op residual/output bytes (resnet20 "
                  "b4, policy none):", file=out)
            for op, nb in planner_bytes.items():
                print(f"    {op:<24} {nb / (1 << 20):8.2f} MiB",
                      file=out)
            print(f"mxlint: {covered} ops covered, {len(missing)} "
                  "missing cost metadata (seed ops/cost.py)", file=out)
        return 1 if partial else 0

    audit_mode = args.precision_audit or args.memory_plan or \
        args.env_audit or args.metric_audit or args.race_audit or \
        args.cachekey_audit or args.determinism_audit
    if not args.check and not args.paths and not audit_mode:
        p.print_usage(file=sys.stderr)
        print("mxlint: nothing to lint (pass symbol JSON paths or "
              "--check)", file=sys.stderr)
        return 2

    try:
        shapes = _parse_shape_args(args.shape)
    except ValueError as e:
        print(f"mxlint: {e}", file=sys.stderr)
        return 2

    findings = []
    try:
        if args.check:
            findings += run_check(out, as_json=args.as_json)
            # the CI gate also covers the precision tiers, a resnet20
            # memory plan at two policies (plan construction must
            # succeed; ME findings only fire against a real capacity),
            # and the env-var doc sync
            findings += run_precision_audit(out, quiet=args.as_json)
            findings += run_memory_plan(
                "resnet20", out, policies=("none", "dots"),
                capacity_gb=args.capacity_gb, quiet=args.as_json)
            findings += run_env_audit(out, quiet=args.as_json)
            findings += run_metric_audit(out, quiet=args.as_json)
            # the dynamic-behavior passes: host races, cache-key
            # completeness, determinism — all pure-AST, no bind cost
            findings += run_race_audit(out, quiet=args.as_json)
            findings += run_cachekey_audit(out, quiet=args.as_json)
            findings += run_determinism_audit(out, quiet=args.as_json)
        if args.precision_audit:
            dtypes = tuple(
                d.strip() for d in
                (args.compute_dtype or "float32,bfloat16").split(",")
                if d.strip())
            findings += run_precision_audit(out, compute_dtypes=dtypes,
                                            as_json=args.as_json)
        if args.memory_plan:
            try:
                findings += run_memory_plan(
                    args.memory_plan, out,
                    policies=tuple(args.policies or ("none",)),
                    batch=args.batch, capacity_gb=args.capacity_gb,
                    optimizer=args.optimizer, n_data=args.num_devices,
                    zero=args.zero, as_json=args.as_json)
            except KeyError:
                print(f"mxlint: unknown model {args.memory_plan!r} "
                      "(bundled: mlp, lenet, alexnet, vgg16, resnet20, "
                      "inception_bn, inception_v3)", file=sys.stderr)
                return 2
        if args.env_audit:
            findings += run_env_audit(out, as_json=args.as_json)
        if args.metric_audit:
            findings += run_metric_audit(out, as_json=args.as_json)
        if args.race_audit:
            findings += run_race_audit(out, as_json=args.as_json)
        if args.cachekey_audit:
            findings += run_cachekey_audit(out, as_json=args.as_json)
        if args.determinism_audit:
            findings += run_determinism_audit(out, as_json=args.as_json)
        for path in args.paths:
            findings += lint_path(path, shapes, out, as_json=args.as_json)
    except FileNotFoundError as e:
        print(f"mxlint: {e}", file=sys.stderr)
        return 2

    errors = [f for f in findings if f["severity"] == "error"]
    if args.as_json:
        json.dump({"findings": findings, "errors": len(errors)}, out,
                  indent=2)
        print(file=out)
    else:
        for f in findings:
            where = f" at node '{f['node']}'" if f.get("node") else ""
            print(f"{f['target']}: {f['rule']} [{f['severity']}]"
                  f"{where}: {f['message']}", file=out)
            if f.get("hint"):
                print(f"    hint: {f['hint']}", file=out)
        print(f"mxlint: {len(findings)} finding(s), {len(errors)} "
              f"error(s)", file=out)

    if errors or (args.strict and findings):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
