"""Benchmark: ResNet-50 training, framework Module.fit vs pure JAX/Flax.

The north star (BASELINE.json): >= 90% of the reference JAX/Flax
samples/sec on the same TPU chip, same operating point — bfloat16
compute over float32 master params, batch 256, SGD momentum. Both sides
run here, back to back, on the same chip:

  * ours    — `mx.mod.Module.fit` on models/resnet.get_symbol(50): the
              product hot loop (fused fwd+bwd+update XLA program ->
              buffer swaps -> metric update) over device-resident
              batches;
  * flax_ref — benchmarks/flax_resnet50.py: linen + optax with TPU best
              practices (NHWC, donated jitted train step), fully
              pre-staged device inputs.

Both sides consume device-resident data so the ratio measures the train
programs; the input pipeline (multiprocess decode + prefetch-to-device)
has its own benchmark, benchmarks/io_bench.py. The two sides are paired
at batch granularity (one forced flax step inside fit's
batch_end_callback after each forced ours batch) and the reported ratio
is the median over all paired laps — the only statistic that survives
the shared tunnel's multi-second latency spikes.

MFU is computed from each side's own compiled-program FLOPs
(`lowered.compile().cost_analysis()['flops']`) against the chip's bf16
peak — a physically-possible MFU (<= ~55% for conv nets on v5e-class)
is the sanity check the raw img/s number lacks.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
`vs_baseline` IS the ours/flax ratio (the 2017 P100 number from
reference docs/how_to/perf.md:179-188 is kept as context only).
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# persistent XLA compile cache: the two ResNet-50 programs dominate wall
# time through the remote-chip tunnel; repeated runs (driver reruns) hit
# the cache and finish in minutes instead
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(os.path.dirname(
                          os.path.abspath(__file__)), ".jax_cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2")


def _log(msg):
    print(f"[bench +{time.perf_counter() - _T0:.0f}s] {msg}",
          file=sys.stderr, flush=True)


_T0 = time.perf_counter()

BATCH = 256
N_BATCHES = 8          # synthetic epoch size (per timed round)
ROUNDS = 5             # interleaved A/B rounds; the reported ratio is the
                       # median of per-round ratios (the shared chip's
                       # throughput drifts minute to minute, so the two
                       # sides must be sampled close together)
NUM_CLASSES = 1000
LR, MOMENTUM = 0.1, 0.9

# bf16 peak FLOP/s per chip by device_kind (MFU denominator)
PEAK_BF16 = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}
REFERENCE_P100_IMG_S = 181.53   # context only (perf.md:179-188)


def _synthetic(rng):
    imgs = rng.rand(N_BATCHES * BATCH, 3, 224, 224).astype(np.float32)
    labels = (rng.rand(N_BATCHES * BATCH) * NUM_CLASSES).astype(
        np.float32)
    return imgs, labels


class _StagedIter:
    """Minimal DataIter over pre-staged device-resident batches.

    Both bench sides consume device-resident inputs so the ratio
    measures the train programs, not the host->device path (the
    product's staging pipeline — PrefetchingIter prefetch-to-device +
    the multiprocess decoder — has its own benchmark, io_bench.py; the
    flax referent gets the even stronger treatment of fully pre-staged
    arrays)."""

    def __init__(self, batches, provide_data, provide_label):
        self._batches = batches
        self.provide_data = provide_data
        self.provide_label = provide_label
        self.batch_size = provide_data[0].shape[0]
        self._i = 0

    def reset(self):
        self._i = 0

    def __iter__(self):
        return self

    def __next__(self):
        if self._i >= len(self._batches):
            raise StopIteration
        b = self._batches[self._i]
        self._i += 1
        return b

    next = __next__


def setup_ours(imgs, labels):
    """Bind + compile + warm; returns (mod, staged_iter, exe, force,
    opt_params) plus the fused program's FLOPs/step."""
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu.models import resnet

    sym = resnet.get_symbol(num_classes=NUM_CLASSES, num_layers=50,
                            image_shape="3,224,224")
    it = mx.io.NDArrayIter(imgs, labels, batch_size=BATCH)
    # pin the accelerator explicitly: the default context is cpu (reference
    # semantics), which on this host would strand params on the CPU backend
    # while jnp ops land on the chip — every node a cross-device transfer
    mod = mx.mod.Module(sym, context=mx.tpu(),
                        compute_dtype=jnp.bfloat16)
    opt_params = {"learning_rate": LR, "momentum": MOMENTUM}

    _log("ours: bind+compile+warm epoch")
    mod.fit(it, num_epoch=1, initializer=mx.initializer.Xavier(),
            optimizer_params=opt_params)
    assert mod._fused_armed, "bench must measure the fused train step"
    exe = mod._exec_group.executor

    _log("ours: staging batches on device")
    it.reset()
    dev = mx.tpu().jax_device()
    staged = []
    for b in it:
        arrs = [mx.nd.NDArray(jax.device_put(a.asjax(), dev))
                for a in b.data]
        labs = [mx.nd.NDArray(jax.device_put(a.asjax(), dev))
                for a in (b.label or [])]
        for a in arrs + labs:
            jax.block_until_ready(a.asjax())
        staged.append(mx.io.DataBatch(arrs, labs, pad=b.pad))
    staged_it = _StagedIter(staged, it.provide_data, it.provide_label)

    def force(param=None):
        # Device-side metrics no longer sync per batch (metric.py
        # _accumulate_device), so force completion by fetching the
        # metric's pending device scalar — 4 bytes, one round trip,
        # exactly symmetric with the flax side's loss fetch. Fall back
        # to an output fetch if the metric has nothing pending.
        m = getattr(param, "eval_metric", None) if param else None
        if m is not None and getattr(m, "_pending", None):
            float(jax.device_get(m._pending[-1][0]))
        else:
            jax.device_get(exe._outputs[0].asjax())

    flops = None
    try:
        arg_vals = exe._arg_vals()
        watched = mod._exec_group._fused_watched
        w = {nm: arg_vals.pop(nm) for nm in watched}
        lrs, wds = mod._fused_lr_wd()
        lowered = mod._exec_group._fused_prog.lower(
            w, arg_vals, exe._aux_vals(), jax.random.PRNGKey(0),
            mod._exec_group._fused_states,
            jnp.asarray([lrs[nm] for nm in watched], jnp.float32),
            jnp.asarray([wds[nm] for nm in watched], jnp.float32))
        cost = lowered.compile().cost_analysis()
        if cost and "flops" in cost:
            flops = float(cost["flops"])
    except Exception as e:
        _log(f"ours: cost_analysis unavailable: {e!r}")
    return (mod, staged_it, exe, force, opt_params), flops


def setup_flax(imgs, labels):
    """Compile + warm; returns a one-forced-step closure."""
    import jax
    from benchmarks.flax_resnet50 import make_train_step

    step, init = make_train_step(BATCH, LR, MOMENTUM, NUM_CLASSES)
    state_box = [init(jax.random.PRNGKey(0))]
    nhwc = np.ascontiguousarray(imgs.transpose(0, 2, 3, 1))
    lab = labels.astype(np.int32)

    def batch(i):
        j = (i % N_BATCHES) * BATCH
        return nhwc[j:j + BATCH], lab[j:j + BATCH]

    flops = None
    try:
        _log("flax: lower+compile")
        cost = step.lower(state_box[0],
                          *batch(0)).compile().cost_analysis()
        if cost and "flops" in cost:
            flops = float(cost["flops"])
    except Exception as e:
        # cost_analysis is best-effort across jax versions, but a failure
        # must be visible — a silent null here hid a NameError for a round
        _log(f"flax: cost_analysis unavailable: {e!r}")

    _log("flax: warm steps + device staging")
    staged = []
    for i in range(N_BATCHES):
        x, y = batch(i)
        xd, yd = jax.device_put(x), jax.device_put(y)
        jax.block_until_ready(xd)
        staged.append((xd, yd))
    for i in range(3):                      # compile + warm
        state_box[0], loss = step(state_box[0], *staged[i % N_BATCHES])
    float(jax.device_get(loss))

    counter = [0]                           # device-step submissions

    def one_step(i):
        # forced completion via scalar fetch: through the remote-chip
        # tunnel block_until_ready returns before execution finishes,
        # which would time async dispatch instead of the train step
        state_box[0], loss = step(state_box[0],
                                  *staged[i % N_BATCHES])
        counter[0] += 1           # timed laps only (warm calls step())
        float(jax.device_get(loss))

    return one_step, flops, counter


def measure_spmd_variant():
    """The ``spmd`` variant row: paired spmd-vs-kvstore lap on the
    local mesh (benchmarks/spmd_vs_kvstore.py), attached to the bench
    JSON so the MULTICHIP series tracks the GSPMD path. Needs >= 2
    devices (one device has no gradient collective to compare); returns
    a skip note otherwise. Run AFTER the main paired laps — it compiles
    and trains its own programs."""
    import jax
    try:
        if len(jax.devices()) < 2:
            return {"skipped": f"{len(jax.devices())} device(s); the "
                    "spmd-vs-kvstore pairing needs a multi-device mesh"}
        from benchmarks.spmd_vs_kvstore import main as spmd_lap
        return spmd_lap(quiet=True)
    except Exception as e:          # the variant must never sink the run
        return {"error": f"{type(e).__name__}: {e}"}


def measure_serve_variant():
    """The ``serve`` variant row: req/s at a p99 SLO under an open-loop
    Poisson load against the continuous-batching server (mxnet_tpu/
    serve) — the second bench axis ROADMAP item 3 names, next to
    img/s. A small MLP keeps the serving overheads (scheduler, pad/
    slice, dispatch) the measured quantity rather than model FLOPs;
    runs on whatever backend the process has (TPU main path and CPU
    fallback both emit it). Never sinks the run."""
    import jax  # noqa: F401  (backend must already be up)
    import numpy as np
    import mxnet_tpu as mx

    SLO_MS = 100
    try:
        data = mx.sym.var("data")
        fc = mx.sym.FullyConnected(data=data, num_hidden=64, name="sv1")
        act = mx.sym.Activation(fc, act_type="relu")
        fc2 = mx.sym.FullyConnected(act, num_hidden=16, name="sv2")
        sym = mx.sym.SoftmaxOutput(fc2, name="softmax")
        mod = mx.mod.Module(sym)
        mod.bind([("data", (8, 32))], [("softmax_label", (8,))],
                 for_training=False)
        mod.init_params(mx.initializer.Xavier())
        server = mx.serve.serve(mod, name="bench", ladder=[1, 2, 4, 8],
                                default_deadline_ms=SLO_MS)
        gen = mx.serve.PoissonLoadGen(
            server,
            lambda i, rng: {"data": rng.rand(1 + i % 3, 32)
                            .astype(np.float32)},
            model="bench", rate=150.0, n_requests=300, seed=0)
        try:
            out = gen.run(slo_ms=SLO_MS)
        finally:
            server.stop()
        stats = server.stats()
        m = stats["models"]["bench"]
        out.update({
            "batch_occupancy": m["batch_occupancy"],
            "padding_waste_pct": m["padding_waste_pct"],
            "dispatches": m["dispatches"],
            "compiles_since_warmup": stats["compiles_since_warmup"],
            "ladder": m["ladder"],
        })
        return out
    except Exception as e:          # the variant must never sink the run
        return {"error": f"{type(e).__name__}: {e}"}


def measure_quant_serve_variant():
    """The ``quant`` serve variant row: req/s at the p99 SLO through the
    continuous-batching server, int8 ladder vs the float ladder, same
    model/load — the int8 inference tier's capacity multiplier
    (ROADMAP 4). The int8 engine binds the quantized graph
    (``compute_dtype="int8"`` → ops/quant.py rewrite), so its rungs pin
    quantized programs; the ``compiles_since_warmup == 0`` contract is
    asserted per side. Runs on whatever backend the process has (the
    dequant-fused Pallas kernel is autotuned on TPU, interpret-gated
    off it). Never sinks the run."""
    import numpy as np
    import mxnet_tpu as mx

    SLO_MS = 100

    def one_side(compute_dtype, tag):
        data = mx.sym.var("data")
        fc = mx.sym.FullyConnected(data=data, num_hidden=256, name="qv1")
        act = mx.sym.Activation(fc, act_type="relu")
        fc2 = mx.sym.FullyConnected(act, num_hidden=64, name="qv2")
        sym = mx.sym.SoftmaxOutput(fc2, name="softmax")
        mod = mx.mod.Module(sym)
        mod.bind([("data", (8, 64))], [("softmax_label", (8,))],
                 for_training=False)
        mod.init_params(mx.initializer.Xavier())
        server = mx.serve.serve(mod, name=tag, ladder=[1, 2, 4, 8],
                                default_deadline_ms=SLO_MS,
                                compute_dtype=compute_dtype)
        gen = mx.serve.PoissonLoadGen(
            server,
            lambda i, rng: {"data": rng.rand(1 + i % 3, 64)
                            .astype(np.float32)},
            model=tag, rate=150.0, n_requests=200, seed=0)
        try:
            out = gen.run(slo_ms=SLO_MS)
        finally:
            server.stop()
        stats = server.stats()
        out["compiles_since_warmup"] = stats["compiles_since_warmup"]
        out["quantized"] = stats["models"][tag]["quantized"]
        return out

    try:
        base = one_side(None, "qbase")
        int8 = one_side("int8", "qint8")
        row = {"float": base, "int8": int8}
        if base.get("req_per_sec") and int8.get("req_per_sec"):
            row["int8_speedup"] = round(
                int8["req_per_sec"] / base["req_per_sec"], 3)
        return row
    except Exception as e:          # the variant must never sink the run
        return {"error": f"{type(e).__name__}: {e}"}


def measure_lm_variant():
    """The ``lm`` variant row: the transformer workload's three axes
    (ROADMAP 1) — training tokens/s + step time through the fused
    Module.fit path, incremental KV-cache decode tokens/s, and a
    max-context-length sweep that walks the context up until the static
    memory planner's ME801 predicted-OOM trips against the device HBM
    capacity. Also attaches the kernel-tier selection table filtered to
    the attention family, so the xla/flash/ring pick per shape lands in
    the payload. Small model on CPU, bench-scale on TPU; never sinks
    the run."""
    import time
    import numpy as np
    import jax
    import mxnet_tpu as mx

    try:
        from mxnet_tpu.models import transformer as tfm
        from mxnet_tpu import kernel_tier
        from mxnet_tpu.analysis import memplan
        from mxnet_tpu.telemetry.mfu import device_hbm_bytes

        on_tpu = jax.default_backend() == "tpu"
        V, D, L, H = (32000, 512, 8, 8) if on_tpu else (128, 64, 2, 4)
        T, B = (1024, 8) if on_tpu else (32, 8)
        n_batches = 8

        sym = tfm.get_symbol(vocab_size=V, d_model=D, n_layer=L,
                             n_head=H, seq_len=T)
        it = tfm.SyntheticLMIter(V, B, T, n_batches=n_batches, seed=0)
        mod = mx.mod.Module(sym)
        steps = []

        def cb(param):
            steps.append(time.perf_counter())

        mod.fit(it, num_epoch=2, optimizer="sgd",
                optimizer_params=(("learning_rate", 0.05),),
                initializer=mx.initializer.Xavier(),
                batch_end_callback=cb)
        # steady state: the second epoch's inter-batch gaps
        laps = np.diff(steps[n_batches:])
        step_s = float(np.median(laps)) if len(laps) else None
        train_tok_s = (B * T / step_s) if step_s else None

        # incremental decode tokens/s through the KV cache
        args, _ = mod.get_params()
        dec_sym = tfm.get_decode_symbol(vocab_size=V, d_model=D,
                                        n_layer=L, n_head=H, capacity=T)
        dec = mx.mod.Module(dec_sym, label_names=[])
        dec.bind([("data", (B, 1))], None, for_training=False)
        dec.init_params(initializer=None, arg_params=args, aux_params={},
                        allow_missing=True)
        drv = tfm.KVCacheDecoder(dec, capacity=T)
        tokens = np.random.RandomState(0).randint(0, V, (B, T))
        drv.step(tokens[:, :1]).asnumpy()          # compile + warm
        drv.reset()
        n_dec = min(T, 64)
        tic = time.perf_counter()
        for t in range(n_dec):
            out = drv.step(tokens[:, t:t + 1])
        out.asnumpy()
        dec_s = time.perf_counter() - tic
        decode_tok_s = B * n_dec / dec_s if dec_s else None

        # max-context sweep: double the context until ME801 trips
        capacity = device_hbm_bytes() or (16 << 30)
        sweep, max_ctx = [], None
        ctx = T
        while ctx <= (1 << 20):
            plan = memplan.plan_symbol(
                tfm.get_symbol(vocab_size=V, d_model=D, n_layer=L,
                               n_head=H, seq_len=ctx),
                {"data": (B, ctx), "softmax_label": (B * ctx,)},
                policy="dots")
            fits = plan["peak_bytes_per_device"] <= capacity
            sweep.append({"context": ctx,
                          "peak_gb": round(
                              plan["peak_bytes_per_device"] / 2**30, 3),
                          "fits": fits})
            if not fits:
                break
            max_ctx = ctx
            ctx *= 2

        attn_rows = [
            {k: d.get(k) for k in ("op", "variant", "reason", "xla_ms",
                                   "pallas_ms", "source", "shapes")}
            for d in kernel_tier.decisions()
            if "attention" in str(d.get("op", ""))]
        return {
            "model": {"vocab": V, "d_model": D, "layers": L, "heads": H,
                      "seq_len": T, "batch": B},
            "train_tokens_per_sec": None if train_tok_s is None
            else round(train_tok_s, 1),
            "step_ms": None if step_s is None else round(step_s * 1e3, 2),
            "decode_tokens_per_sec": None if decode_tok_s is None
            else round(decode_tok_s, 1),
            "max_context": max_ctx,
            "max_context_policy": "dots",
            "hbm_capacity_gb": round(capacity / 2**30, 1),
            "context_sweep": sweep,
            "attention_selection": attn_rows,
        }
    except Exception as e:          # the variant must never sink the run
        return {"error": f"{type(e).__name__}: {e}"}


def measure_lm_mfu_variant():
    """The ``lm_mfu`` flagship row (ISSUE 19): the transformer operating
    point reported the way the paper reports it — training tokens/s WITH
    the model-attributed MFU%, and serving decode tokens/s at slot
    counts {1, 8} for each KV-cache storage tier (f32 cache, int8
    weights, fp8 cache) — plus the decode-attention kernel-tier
    selection table, so the xla/pallas pick and its measured speedup
    ride in the same payload as the throughput they explain.

    MFU% follows the wall-clock honesty rule of the main metric: off
    the PEAKS table (CPU, unknown chips) or when the step time is
    transport-dominated, the percentage is withheld (None) and the
    achieved FLOP/s is recorded instead. ``compiles_since_warmup`` must
    be 0 at every decode point — the fp8 tier rides the same pinned
    rungs as float. Never sinks the run."""
    import time
    import numpy as np
    import jax
    import mxnet_tpu as mx

    try:
        import statistics
        from mxnet_tpu.models import transformer as tfm
        from mxnet_tpu import kernel_tier
        from mxnet_tpu.telemetry import mfu as _mfu

        on_tpu = jax.default_backend() == "tpu"
        V, D, L, H = (32000, 512, 8, 8) if on_tpu else (128, 64, 2, 4)
        T, B = (1024, 8) if on_tpu else (32, 8)
        n_batches = 8

        row = {"model": {"vocab": V, "d_model": D, "layers": L,
                         "heads": H, "seq_len": T, "batch": B}}

        # --- train leg: tokens/s + model-attributed MFU% -------------
        sym = tfm.get_symbol(vocab_size=V, d_model=D, n_layer=L,
                             n_head=H, seq_len=T)
        it = tfm.SyntheticLMIter(V, B, T, n_batches=n_batches, seed=0)
        mod = mx.mod.Module(sym)
        steps = []

        def cb(param):
            steps.append(time.perf_counter())

        mod.fit(it, num_epoch=2, optimizer="sgd",
                optimizer_params=(("learning_rate", 0.05),),
                initializer=mx.initializer.Xavier(),
                batch_end_callback=cb)
        laps = np.diff(steps[n_batches:])      # second epoch only
        step_s = float(np.median(laps)) if len(laps) else None
        row["train_tokens_per_sec"] = round(B * T / step_s, 1) \
            if step_s else None
        row["step_ms"] = round(step_s * 1e3, 2) if step_s else None

        train_flops, mfu_pct, achieved = None, None, None
        try:
            table = _mfu.cost_table(
                sym, {"data": (B, T), "softmax_label": (B * T,)},
                train=True)
            train_flops = table["train_flops"]
            if step_s:
                achieved = train_flops / step_s
            peak, _ = _mfu.device_peaks()
            if peak and step_s:
                # same transport-dominance guard as the headline MFU:
                # a wall step >10x the device-side floor measures the
                # tunnel, not the chip — withhold the percentage
                floor = train_flops / peak
                if step_s <= 10 * floor:
                    mfu_pct = round(100.0 * achieved / peak, 2)
                else:
                    row["mfu_note"] = (
                        f"step {step_s:.3f}s is "
                        f"{step_s / floor:.0f}x the device floor "
                        f"{floor:.4f}s — transport-dominated; MFU% "
                        "withheld")
        except Exception as e:      # attribution must not sink the row
            row["mfu_error"] = f"{type(e).__name__}: {e}"
        row["train_mfu_pct"] = mfu_pct
        row["train_flops_per_step"] = train_flops
        row["achieved_flops_per_sec"] = achieved

        # --- decode leg: tokens/s per cache tier at slots {1, 8} -----
        # f32 = baseline cache; int8 = quantized weights (float cache);
        # fp8 = float weights with the fp8 KV-cache storage tier
        psym = tfm.get_symbol(vocab_size=V, d_model=D, n_layer=L,
                              n_head=H, seq_len=8, include_loss=False,
                              max_seq_len=T)
        pmod = mx.mod.Module(psym, label_names=[])
        pmod.bind([("data", (1, 8))], None, for_training=False)
        pmod.init_params(mx.initializer.Xavier(rnd_type="gaussian",
                                               magnitude=2))
        args, _ = pmod.get_params()
        CAP = 256 if on_tpu else 64
        PROMPT, MAX_NEW = (16, 64) if on_tpu else (4, 16)
        tiers = (("f32", "", None), ("int8", "", "int8"),
                 ("fp8", "fp8", None))
        for tier, cache_dtype, compute_dtype in tiers:
            dsym = tfm.get_decode_symbol(
                vocab_size=V, d_model=D, n_layer=L, n_head=H,
                capacity=CAP, per_slot=True, max_seq_len=T,
                cache_dtype=cache_dtype or None)
            for slots in (1, 8):
                sched = mx.serve.serve_decoder(
                    dsym, args, name=f"mfu_{tier}_{slots}",
                    ladder=[slots], compute_dtype=compute_dtype,
                    start=True)
                rs = np.random.RandomState(slots)
                handles = []
                t0 = time.perf_counter()
                for _ in range(2 * slots):
                    handles.append(sched.submit(
                        rs.randint(0, V, PROMPT).tolist(),
                        max_new_tokens=MAX_NEW))
                toks = sum(len(h.result(timeout=600)) for h in handles)
                elapsed = time.perf_counter() - t0
                stats = sched.stats()
                sched.stop()
                row[f"decode_{tier}_slots{slots}_tokens_per_sec"] = \
                    round(toks / elapsed, 1) if elapsed else None
                row[f"decode_{tier}_slots{slots}"
                    "_compiles_since_warmup"] = \
                    stats["compiles_since_warmup"]
        row["decode_fp8_tokens_per_sec"] = \
            row.get("decode_fp8_slots8_tokens_per_sec")

        # --- decode-attention selection table + measured speedup -----
        attn_rows = [
            {k: d.get(k) for k in ("op", "variant", "reason", "xla_ms",
                                   "pallas_ms", "source", "shapes")}
            for d in kernel_tier.decisions()
            if "attention_decode" in str(d.get("op", ""))]
        row["decode_attention_selection"] = attn_rows
        speedups = [d["xla_ms"] / d["pallas_ms"] for d in attn_rows
                    if d.get("variant") == "pallas"
                    and d.get("xla_ms") and d.get("pallas_ms")]
        row["decode_attn_speedup"] = \
            round(statistics.median(speedups), 2) if speedups else None
        return row
    except Exception as e:          # the variant must never sink the run
        return {"error": f"{type(e).__name__}: {e}"}


def measure_decode_batch_variant():
    """The ``decode_batch`` variant row: aggregate KV-cache decode
    tokens/s through the continuous-batching decode scheduler
    (serve/decode.py) at slot counts {1, 4, 8} under open-loop
    arrivals — the serving-throughput multiplier ROADMAP 3(b) names.
    Each point runs a single-rung slot ladder so the figure isolates
    the slot count; occupancy and the zero-compile contract ride along
    (``compiles_since_warmup`` must be 0 at every point). Small model
    on CPU, bench-scale on TPU; never sinks the run."""
    import time
    import numpy as np
    import jax
    import mxnet_tpu as mx

    try:
        from mxnet_tpu.models import transformer as tfm

        on_tpu = jax.default_backend() == "tpu"
        V, D, L, H = (32000, 512, 8, 8) if on_tpu else (128, 64, 2, 4)
        CAP = 256 if on_tpu else 64
        PROMPT, MAX_NEW = (16, 64) if on_tpu else (4, 16)
        RATE = 200.0            # open-loop arrivals/s (saturating)

        sym = tfm.get_symbol(vocab_size=V, d_model=D, n_layer=L,
                             n_head=H, seq_len=8, include_loss=False,
                             max_seq_len=CAP)
        mod = mx.mod.Module(sym, label_names=[])
        mod.bind([("data", (1, 8))], None, for_training=False)
        mod.init_params(mx.initializer.Xavier(rnd_type="gaussian",
                                              magnitude=2))
        args, _ = mod.get_params()
        dec_sym = tfm.get_decode_symbol(
            vocab_size=V, d_model=D, n_layer=L, n_head=H, capacity=CAP,
            per_slot=True, max_seq_len=CAP)

        rows = {}
        for slots in (1, 4, 8):
            sched = mx.serve.serve_decoder(
                dec_sym, args, name=f"decb{slots}", ladder=[slots],
                start=True)
            rs = np.random.RandomState(slots)
            n_req = 3 * slots
            gaps = rs.exponential(1.0 / RATE, size=n_req)
            handles = []
            t0 = time.perf_counter()
            at = t0
            for i in range(n_req):
                at += gaps[i]
                dt = at - time.perf_counter()
                if dt > 0:
                    time.sleep(dt)
                handles.append(sched.submit(
                    rs.randint(0, V, PROMPT).tolist(),
                    max_new_tokens=MAX_NEW))
            toks = sum(len(h.result(timeout=600)) for h in handles)
            elapsed = time.perf_counter() - t0
            stats = sched.stats()
            sched.stop()
            rows[f"slots{slots}_tokens_per_sec"] = round(
                toks / elapsed, 1) if elapsed else None
            rows[f"slots{slots}_occupancy_mean"] = round(
                stats["tokens"] / (stats["iterations"] * slots), 3) \
                if stats["iterations"] else None
            rows[f"slots{slots}_compiles_since_warmup"] = \
                stats["compiles_since_warmup"]
        if rows.get("slots1_tokens_per_sec") and \
                rows.get("slots8_tokens_per_sec"):
            rows["speedup_8v1"] = round(
                rows["slots8_tokens_per_sec"]
                / rows["slots1_tokens_per_sec"], 2)

        # --- TTFT vs prompt length: chunked prefill against the
        # token-at-a-time path (ISSUE 18).  Long-context decode symbol
        # (capacity past the 2048-token prompt) on one slot, one
        # request in flight, so ttft is pure prefill latency.
        try:
            TCAP = 2048 + 64
            chunk = mx.serve.default_prefill_chunk()
            lsym = tfm.get_symbol(vocab_size=V, d_model=D, n_layer=L,
                                  n_head=H, seq_len=8,
                                  include_loss=False, max_seq_len=TCAP)
            lmod = mx.mod.Module(lsym, label_names=[])
            lmod.bind([("data", (1, 8))], None, for_training=False)
            np.random.seed(7)
            lmod.init_params(mx.initializer.Xavier(rnd_type="gaussian",
                                                   magnitude=2))
            largs, _ = lmod.get_params()

            def lgen(s):
                return tfm.get_decode_symbol(
                    vocab_size=V, d_model=D, n_layer=L, n_head=H,
                    capacity=TCAP, per_slot=True, step_len=s,
                    max_seq_len=TCAP)

            plens = (64, 512, 2048)
            rs = np.random.RandomState(11)
            prompts = {n: rs.randint(0, V, n).tolist() for n in plens}
            curve = {str(n): {} for n in plens}
            for tag, ch in (("nochunk", 1), ("chunk", chunk)):
                sched = mx.serve.serve_decoder(
                    lgen(1), largs, name=f"decb_ttft_{tag}",
                    ladder=[1], start=True,
                    symbol_gen=lgen if ch > 1 else None,
                    prefill_chunk=ch)
                for n in plens:
                    h = sched.submit(prompts[n], max_new_tokens=2)
                    h.result(timeout=600)
                    curve[str(n)][f"{tag}_ms"] = round(h.ttft * 1e3, 2)
                sched.stop()
            for n in plens:
                c = curve[str(n)]
                c["speedup"] = round(c["nochunk_ms"] / c["chunk_ms"], 2)
            rows["ttft_curve"] = curve
            rows["ttft_prefill_chunk"] = chunk
            rows["ttft_2048_ms"] = curve["2048"]["chunk_ms"]
            rows["ttft_2048_speedup"] = curve["2048"]["speedup"]
        except Exception as e:      # sub-row must not sink the variant
            rows["ttft_error"] = f"{type(e).__name__}: {e}"

        # --- speculative decoding sub-row: a seeded draft/target pair
        # trained to memorise a deterministic Markov map (next token is
        # an affine function of the current one) so acceptance is high
        # by construction; the speedup is spec vs non-spec tokens/s at
        # slots 8 on the SAME trained target.  MXNET_SERVE_SPEC_DRAFT
        # picks the draft preset ("<d_model>x<n_layer>", "off" skips).
        draft_preset = os.environ.get("MXNET_SERVE_SPEC_DRAFT", "64x1")
        try:
            if draft_preset.strip().lower() in ("off", "none", "0", ""):
                rows["spec_decode"] = {"skipped":
                                       f"MXNET_SERVE_SPEC_DRAFT="
                                       f"{draft_preset}"}
            else:
                dd, dl = (int(x) for x in
                          draft_preset.lower().split("x"))
                SV, ST, SCAP = 128, 16, 64
                TD, TL, SH = 512, 6, 8
                K = mx.serve.default_spec_k()

                def _walk(start, length):
                    out, cur = [], int(start) % SV
                    for _ in range(length):
                        out.append(cur)
                        cur = (7 * cur + 11) % SV
                    return out

                def _markov_iter(B, n_batches, seed):
                    it = tfm.SyntheticLMIter(SV, B, ST, n_batches,
                                             seed)
                    rs2 = np.random.RandomState(seed)
                    for i in range(n_batches):
                        s = np.stack([
                            _walk(rs2.randint(0, SV), ST + 1)
                            for _ in range(B)]).astype(np.int32)
                        it._data[i] = mx.nd.array(s[:, :ST])
                        it._label[i] = mx.nd.array(
                            s[:, 1:].reshape(-1).astype(np.float32))
                    return it

                def _fit(d_model, n_layer, seed):
                    np.random.seed(seed)
                    m = mx.mod.Module(tfm.get_symbol(
                        vocab_size=SV, d_model=d_model,
                        n_layer=n_layer, n_head=SH, seq_len=ST,
                        include_loss=True, max_seq_len=SCAP))
                    m.fit(_markov_iter(16, 32, seed), num_epoch=6,
                          optimizer="sgd",
                          optimizer_params=(("learning_rate", 0.1),
                                            ("momentum", 0.9)),
                          initializer=mx.initializer.Xavier(
                              rnd_type="gaussian", magnitude=2))
                    a, _ = m.get_params()
                    return a

                def _spec_gen(d_model, n_layer):
                    return lambda s: tfm.get_decode_symbol(
                        vocab_size=SV, d_model=d_model,
                        n_layer=n_layer, n_head=SH, capacity=SCAP,
                        per_slot=True, step_len=s, max_seq_len=SCAP)

                targs = _fit(TD, TL, seed=21)
                dargs = _fit(dd, dl, seed=22)
                sprompts = [_walk(3 + 11 * i, 8) for i in range(8)]
                tps = {}
                acceptance = None
                for tag in ("spec", "base"):
                    tgen = _spec_gen(TD, TL)
                    sched = mx.serve.serve_decoder(
                        tgen(1), targs, name=f"decb_{tag}", ladder=[8],
                        start=True, symbol_gen=tgen, prefill_chunk=8,
                        draft_symbol_gen=(_spec_gen(dd, dl)
                                          if tag == "spec" else None),
                        draft_params=(dargs if tag == "spec"
                                      else None),
                        spec_k=K if tag == "spec" else None)
                    hs = [sched.submit(p, max_new_tokens=32)
                          for p in sprompts]
                    t0 = time.perf_counter()
                    toks = sum(len(h.result(timeout=600)) for h in hs)
                    dt = time.perf_counter() - t0
                    st = sched.stats()
                    sched.stop()
                    tps[tag] = toks / dt if dt else None
                    if tag == "spec":
                        acceptance = st["spec"]["acceptance"]
                rows["spec_decode"] = {
                    "draft": draft_preset, "k": K,
                    "acceptance": acceptance,
                    "tokens_per_sec": round(tps["spec"], 1),
                    "base_tokens_per_sec": round(tps["base"], 1),
                    "model": {"vocab": SV, "d_model": TD, "layers": TL,
                              "heads": SH, "capacity": SCAP},
                }
                if tps.get("spec") and tps.get("base"):
                    rows["spec_speedup"] = round(
                        tps["spec"] / tps["base"], 2)
        except Exception as e:      # sub-row must not sink the variant
            rows["spec_decode"] = {"error": f"{type(e).__name__}: {e}"}

        # --- prefix-cache hit-rate point: 8 requests sharing a system
        # prefix via submit(prefix_id=); the first is the cold capture,
        # the rest join at cursor C off the stored rows.
        try:
            pr = mx.serve.serve_decoder(
                dec_sym, args, name="decb_prefix", ladder=[4],
                start=True, prefix_cache_mb=8)
            rsp = np.random.RandomState(5)
            shared = rsp.randint(0, V, CAP // 2).tolist()
            cold_ms, warm = None, []
            for i in range(8):
                h = pr.submit(shared + [1 + i], max_new_tokens=4,
                              prefix_id="bench-sys-prompt")
                h.result(timeout=600)
                if i == 0:
                    cold_ms = round(h.ttft * 1e3, 2)
                else:
                    warm.append(h.ttft * 1e3)
            pst = pr.stats()["prefix"]
            pr.stop()
            rows["prefix_hit_rate"] = pst["hit_rate"]
            rows["prefix"] = {
                "hits": pst["hits"], "misses": pst["misses"],
                "entries": pst["entries"], "bytes": pst["bytes"],
                "cold_ttft_ms": cold_ms,
                "warm_ttft_ms": round(float(np.mean(warm)), 2),
            }
        except Exception as e:      # sub-row must not sink the variant
            rows["prefix_error"] = f"{type(e).__name__}: {e}"

        rows.update({
            "model": {"vocab": V, "d_model": D, "layers": L, "heads": H,
                      "capacity": CAP},
            "prompt_len": PROMPT, "max_new_tokens": MAX_NEW,
            "open_loop_rate_req_s": RATE,
        })
        return rows
    except Exception as e:          # the variant must never sink the run
        return {"error": f"{type(e).__name__}: {e}"}


def measure_remat_memory_variant():
    """Residual-byte delta per remat policy at the resnet20 bench point
    (benchmarks/remat_memory.py): the roofline-side record of what
    ``MXNET_REMAT_POLICY`` frees and which batch bucket that admits.
    Never sinks the run."""
    try:
        from benchmarks.remat_memory import main as remat_lap
        return remat_lap(quiet=True)
    except Exception as e:          # the variant must never sink the run
        return {"error": f"{type(e).__name__}: {e}"}


def kernel_tier_selection_table():
    """The kernel-tier audit for the BENCH payload: per-op selection
    decisions (variant, reason, measured ms) + cache stats, so the r06
    measurement lands with the selection evidence attached."""
    try:
        from mxnet_tpu import kernel_tier
        rows = [{k: d.get(k) for k in ("op", "variant", "reason",
                                       "xla_ms", "pallas_ms", "source",
                                       "is_train")}
                for d in kernel_tier.decisions()]
        return {"mode": os.environ.get("MXNET_KERNEL_TIER", "auto"),
                "decisions": rows, "cache": kernel_tier.cache_info()}
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}


def measure_ckpt_variant():
    """The ``ckpt`` variant row: exposed training stall per snapshot,
    async vs synchronous write, at the resnet20 bench point
    (benchmarks/checkpoint_stall.py). The acceptance gate of the
    async-checkpointing layer is exposed_ratio < 0.10. Runs on
    whatever backend the process has; never sinks the run."""
    try:
        from benchmarks.checkpoint_stall import main as ckpt_lap
        return ckpt_lap(quiet=True)
    except Exception as e:          # the variant must never sink the run
        return {"error": f"{type(e).__name__}: {e}"}


def run_cpu_fallback():
    """Reduced ours-only measurement on the CPU backend.

    Runs when the accelerator tunnel is down: the paired A/B ResNet-50
    protocol is meaningless on CPU (and takes hours), so this measures
    the product hot loop — the fused/scan train program through
    Module.fit — on a CIFAR-scale ResNet-20 and reports it under a
    ``*_cpu_fallback`` metric with ``vs_baseline: null``, so BENCH_r*
    records a real number instead of only nulls (BENCH_r05).
    """
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu.models import resnet

    batch, n_batches, classes = 32, 8, 10
    rng = np.random.RandomState(0)
    imgs = rng.rand(n_batches * batch, 3, 32, 32).astype(np.float32)
    labels = (rng.rand(n_batches * batch) * classes).astype(np.float32)

    sym = resnet.get_symbol(num_classes=classes, num_layers=20,
                            image_shape="3,32,32")
    it = mx.io.NDArrayIter(imgs, labels, batch_size=batch)
    mod = mx.mod.Module(sym, context=mx.cpu())
    opt_params = {"learning_rate": LR, "momentum": MOMENTUM}

    _log("cpu fallback: bind+compile+warm epoch")
    mod.fit(it, num_epoch=1, initializer=mx.initializer.Xavier(),
            optimizer_params=opt_params)

    _log("cpu fallback: timed epochs")
    laps = []
    lap = [time.perf_counter()]

    def cb(param):
        # force completion symmetrically with the main protocol: fetch
        # the metric's pending device scalar
        m = param.eval_metric
        if getattr(m, "_pending", None):
            float(jax.device_get(m._pending[-1][0]))
        laps.append(time.perf_counter() - lap[0])
        lap[0] = time.perf_counter()

    for _ in range(2):
        it.reset()
        lap[0] = time.perf_counter()
        mod.fit(it, num_epoch=1, optimizer_params=opt_params,
                batch_end_callback=cb)
    import statistics
    img_s = batch / statistics.median(laps)

    # roofline attribution still applies off-TPU (no peak -> achieved
    # FLOP/s only, MFU withheld); keeps the MFU plumbing exercised in
    # fallback runs
    from mxnet_tpu.telemetry import mfu as _mfu
    roofline_rows, achieved = None, None
    try:
        table = _mfu.cost_table(sym, {"data": (batch, 3, 32, 32),
                                      "softmax_label": (batch,)},
                                train=True)
        achieved = table["train_flops"] / statistics.median(laps)
        roofline_rows = [
            {"op": r["op"], "share": round(r["share"], 3),
             "ai": round(r["ai"], 1), "bound": r["bound"]}
            for r in _mfu.roofline(table, train=True, top=6)]
    except Exception:
        pass
    print(json.dumps({
        "metric": "resnet20_cifar_bf16off_b32_train_img_per_sec"
                  "_cpu_fallback",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": None,
        "device": "cpu",
        "n_laps": len(laps),
        "achieved_flops_per_sec": achieved,
        "roofline": roofline_rows,
        "spmd": measure_spmd_variant(),
        "serve": measure_serve_variant(),
        "quant": measure_quant_serve_variant(),
        "ckpt": measure_ckpt_variant(),
        "remat_memory": measure_remat_memory_variant(),
        "lm": measure_lm_variant(),
        "lm_mfu": measure_lm_mfu_variant(),
        "decode_batch": measure_decode_batch_variant(),
        "kernel_tier_selection": kernel_tier_selection_table(),
        "note": "accelerator backend unavailable; ours-only fused-step "
                "throughput on the XLA CPU backend at a CIFAR-scale "
                "operating point — NOT comparable to the flax-paired "
                "TPU metric, recorded so the benchmark series carries "
                "a signal instead of nulls",
    }))


def _cpu_fallback_subprocess(reason):
    """Re-exec this script on the CPU backend in a fresh process.

    The wedged accelerator discovery holds jax's backend-init lock in
    THIS process, so the fallback must run in a subprocess with
    JAX_PLATFORMS=cpu pinned from the start. Prints the child's JSON
    line (with the outer failure attached) and returns its exit code.
    """
    import subprocess
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("JAX_PLATFORM_NAME", None)
    # 8 virtual devices so the spmd variant row still measures a real
    # mesh (matches the tier-1 suite's simulated-multichip environment)
    xla_flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in xla_flags:
        env["XLA_FLAGS"] = (xla_flags +
                            " --xla_force_host_platform_device_count=8"
                            ).strip()
    _log(f"accelerator unavailable ({reason}); "
         "re-running on the CPU backend")
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--cpu-fallback"],
            env=env, capture_output=True, text=True, timeout=2400)
    except subprocess.TimeoutExpired:
        print(json.dumps({
            "metric": "resnet20_cifar_bf16off_b32_train_img_per_sec"
                      "_cpu_fallback",
            "value": None, "unit": "img/s", "vs_baseline": None,
            "error": f"cpu fallback timed out; original failure: "
                     f"{reason}"}))
        return 1
    sys.stderr.write(proc.stderr[-2000:])
    line = None
    for cand in reversed(proc.stdout.strip().splitlines()):
        if cand.startswith("{"):
            line = cand
            break
    if proc.returncode == 0 and line:
        payload = json.loads(line)
        payload["fallback_reason"] = reason
        print(json.dumps(payload))
        return 0
    print(json.dumps({
        "metric": "resnet20_cifar_bf16off_b32_train_img_per_sec"
                  "_cpu_fallback",
        "value": None, "unit": "img/s", "vs_baseline": None,
        "error": f"cpu fallback failed (rc={proc.returncode}); "
                 f"original failure: {reason}"}))
    return 1


class _PairedRound:
    """Batch-granularity A/B pairing inside one fit epoch.

    The shared tunnel's throughput drifts on sub-minute scales — more
    than the difference being measured — so timing a whole flax epoch
    and then a whole fit epoch samples two different tunnels. Instead
    ONE flax step runs (forced) inside Module.fit's batch_end_callback
    after each of our batches (forced): both sides accumulate laps over
    the same seconds, cancelling drift to first order, while ours still
    runs the unmodified product hot loop (the callback is the standard
    Speedometer slot).
    """

    def __init__(self, flax_one_step, force_ours):
        self._flax = flax_one_step
        self._force = force_ours
        self.ours_laps = []
        self.flax_laps = []
        self._i = 0
        self._lap = None

    def start(self):
        self._lap = time.perf_counter()

    def __call__(self, param):             # batch_end_callback
        self._force(param)
        self.ours_laps.append(time.perf_counter() - self._lap)
        tic = time.perf_counter()
        self._flax(self._i)
        self._i += 1
        self.flax_laps.append(time.perf_counter() - tic)
        self._lap = time.perf_counter()


def main():
    import statistics
    import threading

    import jax

    # Bounded backend startup: a dead chip tunnel makes jax.devices()
    # block indefinitely inside backend discovery — fail legibly with a
    # JSON error instead of hanging the driver. (Compiles are NOT under
    # this timeout; only backend init.)
    ready = threading.Event()
    box, err = [], []

    def _init():
        try:
            box.append(jax.devices())
        except Exception as e:          # report the real failure, not
            err.append(f"{type(e).__name__}: {e}")   # a fake timeout
        finally:
            ready.set()

    threading.Thread(target=_init, daemon=True).start()
    if not ready.wait(900) or err:
        reason = err[0] if err else (
            "TPU backend unavailable: jax.devices() did not return "
            "within 900s (tunnel down?)")
        # don't exit 1 with only nulls: measure the CPU backend instead
        # (fresh subprocess — this process's backend init is wedged)
        sys.exit(_cpu_fallback_subprocess(reason))
    dev = box[0][0]
    peak = PEAK_BF16.get(dev.device_kind)
    rng = np.random.RandomState(0)
    imgs, labels = _synthetic(rng)

    flax_one_step, flax_flops, flax_steps = setup_flax(imgs, labels)
    (mod, it, exe, force_ours, opt_params), ours_flops = \
        setup_ours(imgs, labels)

    # per-LAP pairing: each batch yields one (ours_dt, flax_dt) pair
    # sampled within the same seconds; medians over all laps are robust
    # to the tunnel's multi-second latency spikes, which poison any
    # sum- or epoch-level statistic (observed: identical code measured
    # at 3.2s/batch and 21.5s/batch thirty minutes apart)
    import gc
    ours_laps, flax_laps = [], []
    for r in range(ROUNDS):
        it.reset()
        pr = _PairedRound(flax_one_step, force_ours)
        # a GC pause lands in whichever lap is running when it fires —
        # asymmetric noise (ours' lap has more Python allocation than the
        # flax closure); collect between rounds, never inside one
        gc.collect()
        gc.disable()
        pr.start()
        try:
            mod.fit(it, num_epoch=1, optimizer_params=opt_params,
                    batch_end_callback=pr)
        finally:
            # an exception mid-round must not leave GC off for the rest
            # of the process (ADVICE r5)
            gc.enable()
        # drop each round's first lap from BOTH sides: it carries fit's
        # epoch prologue (iterator/metric reset, re-bind guards), which
        # the flax closure has no analog of — steady-state throughput is
        # the comparison; the exclusion count is recorded in the JSON
        pr.ours_laps = pr.ours_laps[1:]
        pr.flax_laps = pr.flax_laps[1:]
        o = BATCH / statistics.median(pr.ours_laps)
        f = BATCH / statistics.median(pr.flax_laps)
        _log(f"round {r}: ours {o:.1f} img/s, flax {f:.1f} img/s "
             f"(median lap), ratio {o / f:.2f}")
        ours_laps.extend(pr.ours_laps)
        flax_laps.extend(pr.flax_laps)
    lap_ratios = sorted(f / o for o, f in zip(ours_laps, flax_laps))
    ratio = statistics.median(lap_ratios)
    ours_img_s = BATCH / statistics.median(ours_laps)
    flax_img_s = BATCH / statistics.median(flax_laps)
    ratios = lap_ratios          # reported per-lap, sorted

    def _lap_summary(laps):
        s = sorted(laps)
        pick = lambda q: s[min(len(s) - 1, int(q * len(s)))]
        return {"p10": round(pick(0.10), 3), "p50": round(pick(0.50), 3),
                "p90": round(pick(0.90), 3), "n": len(s)}

    # methodology self-check (frozen r04 paired-lap method): each lap is
    # exactly one ours fused batch (fit's batch_end_callback fires once
    # per batch) followed by exactly one forced flax step; the counters
    # prove both sides submitted the same number of device steps
    steps_ours = len(ours_laps)
    steps_flax = flax_steps[0]              # one_step calls = timed laps
    paired_ok = (steps_ours == len(lap_ratios)
                 == ROUNDS * (N_BATCHES - 1)
                 and steps_flax == ROUNDS * N_BATCHES)

    # on-device Pallas kernel smoke (AFTER the paired laps so its
    # compiles/executions never contend with the measured rounds):
    # Mosaic-compiles flash attention + fused SGD on the real backend and
    # checks numerics vs the XLA compositions (VERDICT r4 #2)
    _log("pallas smoke (on-device Mosaic compile)")
    from benchmarks.pallas_smoke import run_pallas_smoke
    pallas_smoke = run_pallas_smoke()
    for part in list(pallas_smoke):
        if isinstance(pallas_smoke[part], dict):
            pallas_smoke[part].pop("traceback", None)

    # spmd variant (also after the paired laps, same reasoning): the
    # GSPMD path vs the kvstore-overlap path on this host's mesh
    _log("spmd variant (spmd_vs_kvstore paired lap)")
    spmd_variant = measure_spmd_variant()

    # serve variant (also post-laps): req/s at a p99 SLO through the
    # continuous-batching server — the second bench axis (ROADMAP 3)
    _log("serve variant (Poisson open-loop vs p99 SLO)")
    serve_variant = measure_serve_variant()

    # quant variant: the same serve protocol, int8 ladder vs float —
    # the low-precision tier's capacity multiplier (ROADMAP 4)
    _log("quant variant (int8 vs float serve ladder)")
    quant_variant = measure_quant_serve_variant()

    # ckpt variant: async-vs-sync exposed snapshot stall (ROADMAP 5)
    _log("ckpt variant (checkpoint_stall paired lap)")
    ckpt_variant = measure_ckpt_variant()

    # remat variant: per-policy residual bytes + admitted batch bucket
    _log("remat variant (residual bytes per policy)")
    remat_variant = measure_remat_memory_variant()

    # lm variant: transformer tokens/s + KV-decode + max-context sweep
    # (ROADMAP 1) — the attention xla/flash/ring selection table rides in
    _log("lm variant (transformer train/decode/max-context)")
    lm_variant = measure_lm_variant()

    # lm_mfu flagship variant: train MFU% + per-cache-tier decode
    # tokens/s + the decode-attention selection table (ISSUE 19)
    _log("lm_mfu variant (transformer MFU flagship)")
    lm_mfu_variant = measure_lm_mfu_variant()

    # decode_batch variant: continuous-batching aggregate decode
    # tokens/s at slots {1, 4, 8} (ROADMAP 3b)
    _log("decode_batch variant (slot-pooled continuous batching)")
    decode_batch_variant = measure_decode_batch_variant()

    # per-op MFU attribution + roofline from the registry cost metadata
    # (telemetry/mfu.py): coverage is attributed FLOPs over the XLA
    # compiled-program count — the honesty check on the per-op numbers
    from mxnet_tpu.telemetry import mfu as _mfu
    from mxnet_tpu.ops.cost import optimizer_flops as _opt_flops
    roofline_rows, mfu_coverage, attributed_flops = None, None, None
    try:
        table = _mfu.cost_table(
            mod._symbol, {"data": (BATCH, 3, 224, 224),
                          "softmax_label": (BATCH,)}, train=True)
        n_params = sum(int(np.prod(a.shape))
                       for a in (mod._arg_params or {}).values())
        attributed_flops = table["train_flops"] + \
            _opt_flops("sgd_mom", n_params)
        if ours_flops:
            mfu_coverage = round(attributed_flops / ours_flops, 3)
        peak_flops, peak_bw = _mfu.device_peaks(dev.device_kind)
        roofline_rows = [
            {"op": r["op"], "share": round(r["share"], 3),
             "ai": round(r["ai"], 1), "bound": r["bound"],
             "attainable_frac": round(r.get("attainable_frac", 0), 3)}
            for r in _mfu.roofline(table, peak_flops, peak_bw,
                                   train=True, top=8)]
    except Exception as e:
        _log(f"mfu attribution unavailable: {e!r}")

    # MFU from wall-clock is only a measurement when the wall clock is
    # actually dominated by device compute. Through the shared-chip tunnel
    # the step time can be >100x the device-side floor (flops/peak); in
    # that regime publishing flops/(peak*step_time) would present RPC
    # latency as a chip-utilization figure. Null it instead, with the
    # floor ratio recorded so the reader can see why.
    mfu_note = None

    def mfu(img_s, flops):
        nonlocal mfu_note
        if not (peak and flops):
            return None
        step_time = BATCH / img_s
        device_floor = flops / peak
        if step_time > 10 * device_floor:
            mfu_note = (f"wall step time {step_time:.2f}s is "
                        f"{step_time / device_floor:.0f}x the device-side "
                        f"floor {device_floor:.3f}s — transport-dominated; "
                        "wall-clock MFU withheld")
            return None
        return round(flops / (peak * step_time), 4)

    print(json.dumps({
        "metric": "resnet50_bf16_b256_train_img_per_sec_vs_flax_1chip",
        "value": round(ours_img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(ratio, 3),
        "flax_ref_img_s": round(flax_img_s, 2),
        "ratio_vs_flax": round(ratio, 3),
        "lap_ratios_sorted": [round(r, 3) for r in ratios],
        "n_paired_laps": len(ratios),
        "lap_ratio_p10": round(ratios[int(0.10 * len(ratios))], 3),
        "ours_lap_seconds": _lap_summary(ours_laps),
        "flax_lap_seconds": _lap_summary(flax_laps),
        "paired_step_check": {"ours_timed_laps": steps_ours,
                              "flax_device_steps": steps_flax,
                              "warmup_laps_excluded_per_round": 1,
                              "consistent": paired_ok},
        "pallas_smoke": pallas_smoke,
        "spmd": spmd_variant,
        "serve": serve_variant,
        "quant": quant_variant,
        "ckpt": ckpt_variant,
        "remat_memory": remat_variant,
        "lm": lm_variant,
        "lm_mfu": lm_mfu_variant,
        "decode_batch": decode_batch_variant,
        "kernel_tier_selection": kernel_tier_selection_table(),
        "mfu_ours": mfu(ours_img_s, ours_flops),
        "mfu_flax": mfu(flax_img_s, flax_flops),
        "mfu_model_attributed": mfu(ours_img_s, attributed_flops),
        "mfu_coverage": mfu_coverage,
        "roofline": roofline_rows,
        "kernel_tier": os.environ.get("MXNET_KERNEL_TIER", "auto"),
        "mfu_note": mfu_note,
        "flops_per_step_ours": ours_flops,
        "flops_per_step_flax": flax_flops,
        "device": dev.device_kind,
        "vs_p100_context": round(ours_img_s / REFERENCE_P100_IMG_S, 1),
        "env_note": "remote-tunneled shared chip: per-execution RPC "
                    "latency dominates absolute img/s and drifts on "
                    "sub-minute scales (measured flax epochs 19-80 "
                    "img/s in one session), so both sides run on "
                    "device-resident inputs, paired at BATCH "
                    "granularity (one forced flax step inside "
                    "Module.fit's batch_end_callback after each forced "
                    "ours batch), and the median over all paired laps "
                    "is the signal; input pipeline is benched "
                    "separately (io_bench.py). Across-SESSION "
                    "dispersion remains: back-to-back runs of this "
                    "unchanged script measured ratio 1.137 and 0.956 "
                    "(benchmarks/results/), with within-run rounds "
                    "tight in both — treat any single run as one "
                    "sample of a ~0.95-1.15 session distribution",
    }))


if __name__ == "__main__":
    if "--cpu-fallback" in sys.argv[1:]:
        run_cpu_fallback()
    else:
        main()
