"""Benchmark: ResNet-50 training throughput (img/s) on one TPU chip.

Mirrors the reference's headline number — train_imagenet.py ResNet-50,
batch 32 (reference: docs/how_to/perf.md:179-188, P100 = 181.53 img/s).
``vs_baseline`` is measured against that P100 figure (BASELINE.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
from __future__ import annotations

import json
import time

import numpy as np

BASELINE_P100_IMG_S = 181.53
BATCH = 32
WARMUP = 3
STEPS = 12


def main():
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu.models import resnet
    from mxnet_tpu.executor import _build_graph_runner
    from __graft_entry__ import _build_params

    sym = resnet.get_symbol(num_classes=1000, num_layers=50,
                            image_shape="3,224,224")
    shapes = {"data": (BATCH, 3, 224, 224), "softmax_label": (BATCH,)}
    runner, arg_names, aux_names, loss_mask = _build_graph_runner(sym)
    args, aux = _build_params(sym, shapes)
    rng_np = np.random.RandomState(0)
    args["data"] = jnp.asarray(
        rng_np.rand(*shapes["data"]).astype(np.float32))
    args["softmax_label"] = jnp.asarray(
        (rng_np.rand(BATCH) * 1000).astype(np.float32))
    param_names = [nm for nm in arg_names if nm not in shapes]
    momenta = {nm: jnp.zeros_like(args[nm]) for nm in param_names}
    lr, mom = 0.1, 0.9

    def train_step(arg_vals, aux_vals, mom_vals, rng):
        """Full training step: fwd+bwd+SGD-momentum in ONE XLA program."""
        watched = {nm: arg_vals[nm] for nm in param_names}
        rest = {nm: arg_vals[nm] for nm in shapes}

        def f(w):
            outs, new_aux = runner({**rest, **w}, aux_vals, True, rng)
            return outs, new_aux

        outs, vjp_fn, new_aux = jax.vjp(f, watched, has_aux=True)
        heads = [jnp.ones_like(o) if il else jnp.zeros_like(o)
                 for o, il in zip(outs, loss_mask)]
        (grads,) = vjp_fn(heads)
        new_params, new_mom = {}, {}
        for nm in param_names:
            m = mom * mom_vals[nm] - lr * grads[nm] / BATCH
            new_mom[nm] = m
            new_params[nm] = arg_vals[nm] + m
        return {**rest, **new_params}, new_aux, new_mom

    jitted = jax.jit(train_step, donate_argnums=(0, 1, 2))
    key = jax.random.PRNGKey(0)

    for i in range(WARMUP):
        args, aux, momenta = jitted(args, aux, momenta,
                                    jax.random.fold_in(key, i))
    jax.block_until_ready(args["conv0_weight"])

    tic = time.perf_counter()
    for i in range(STEPS):
        args, aux, momenta = jitted(args, aux, momenta,
                                    jax.random.fold_in(key, 100 + i))
    jax.block_until_ready(args["conv0_weight"])
    toc = time.perf_counter()

    img_s = BATCH * STEPS / (toc - tic)
    print(json.dumps({
        "metric": "resnet50_train_img_per_sec_batch32_1chip",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_P100_IMG_S, 3),
    }))


if __name__ == "__main__":
    main()
