"""Benchmark: ResNet-50 training, framework Module.fit vs pure JAX/Flax.

The north star (BASELINE.json): >= 90% of the reference JAX/Flax
samples/sec on the same TPU chip, same operating point — bfloat16
compute over float32 master params, batch 256, SGD momentum. Both sides
run here, back to back, on the same chip:

  * ours    — `mx.mod.Module.fit` on models/resnet.get_symbol(50): the
              product hot loop (iterator -> fused fwd+bwd+update XLA
              program -> metric update), nothing bypassed;
  * flax_ref — benchmarks/flax_resnet50.py: linen + optax with TPU best
              practices (NHWC, donated jitted train step).

MFU is computed from each side's own compiled-program FLOPs
(`lowered.compile().cost_analysis()['flops']`) against the chip's bf16
peak — a physically-possible MFU (<= ~55% for conv nets on v5e-class)
is the sanity check the raw img/s number lacks.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
`vs_baseline` IS the ours/flax ratio (the 2017 P100 number from
reference docs/how_to/perf.md:179-188 is kept as context only).
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# persistent XLA compile cache: the two ResNet-50 programs dominate wall
# time through the remote-chip tunnel; repeated runs (driver reruns) hit
# the cache and finish in minutes instead
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(os.path.dirname(
                          os.path.abspath(__file__)), ".jax_cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2")


def _log(msg):
    print(f"[bench +{time.perf_counter() - _T0:.0f}s] {msg}",
          file=sys.stderr, flush=True)


_T0 = time.perf_counter()

BATCH = 256
N_BATCHES = 4          # synthetic epoch size (per timed round)
ROUNDS = 3             # interleaved A/B rounds; the reported ratio is the
                       # median of per-round ratios (the shared chip's
                       # throughput drifts minute to minute, so the two
                       # sides must be sampled close together)
NUM_CLASSES = 1000
LR, MOMENTUM = 0.1, 0.9

# bf16 peak FLOP/s per chip by device_kind (MFU denominator)
PEAK_BF16 = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}
REFERENCE_P100_IMG_S = 181.53   # context only (perf.md:179-188)


def _synthetic(rng):
    imgs = rng.rand(N_BATCHES * BATCH, 3, 224, 224).astype(np.float32)
    labels = (rng.rand(N_BATCHES * BATCH) * NUM_CLASSES).astype(
        np.float32)
    return imgs, labels


def setup_ours(imgs, labels):
    """Bind + compile + warm; returns a timed-round closure (one fit
    epoch of N_BATCHES steps through the product hot loop) and the fused
    program's FLOPs/step."""
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu.models import resnet

    sym = resnet.get_symbol(num_classes=NUM_CLASSES, num_layers=50,
                            image_shape="3,224,224")
    it = mx.io.NDArrayIter(imgs, labels, batch_size=BATCH)
    # pin the accelerator explicitly: the default context is cpu (reference
    # semantics), which on this host would strand params on the CPU backend
    # while jnp ops land on the chip — every node a cross-device transfer
    mod = mx.mod.Module(sym, context=mx.tpu(),
                        compute_dtype=jnp.bfloat16)
    opt_params = {"learning_rate": LR, "momentum": MOMENTUM}

    _log("ours: bind+compile+warm epoch")
    mod.fit(it, num_epoch=1, initializer=mx.initializer.Xavier(),
            optimizer_params=opt_params)
    assert mod._fused_armed, "bench must measure the fused train step"
    exe = mod._exec_group.executor

    def timed_round():
        it.reset()
        tic = time.perf_counter()
        mod.fit(it, num_epoch=1, optimizer_params=opt_params)
        # scalar fetch forces the full chain (block_until_ready is
        # unreliable through the tunnel); fit's per-batch metric pulls
        # already force most of it
        float(jax.device_get(exe.arg_dict["fc1_weight"].asjax().ravel()[0]))
        return N_BATCHES * BATCH / (time.perf_counter() - tic)

    flops = None
    try:
        arg_vals = exe._arg_vals()
        w = {nm: arg_vals.pop(nm)
             for nm in mod._exec_group._fused_watched}
        lowered = mod._exec_group._fused_prog.lower(
            w, arg_vals, exe._aux_vals(), jax.random.PRNGKey(0),
            mod._exec_group._fused_states, *mod._fused_lr_wd())
        cost = lowered.compile().cost_analysis()
        if cost and "flops" in cost:
            flops = float(cost["flops"])
    except Exception as e:
        _log(f"ours: cost_analysis unavailable: {e!r}")
    return timed_round, flops


def setup_flax(imgs, labels):
    import jax
    from benchmarks.flax_resnet50 import make_train_step

    step, init = make_train_step(BATCH, LR, MOMENTUM, NUM_CLASSES)
    state = init(jax.random.PRNGKey(0))
    nhwc = np.ascontiguousarray(imgs.transpose(0, 2, 3, 1))
    lab = labels.astype(np.int32)

    def batch(i):
        j = (i % N_BATCHES) * BATCH
        return nhwc[j:j + BATCH], lab[j:j + BATCH]

    flops = None
    try:
        _log("flax: lower+compile")
        cost = step.lower(state, *batch(0)).compile().cost_analysis()
        if cost and "flops" in cost:
            flops = float(cost["flops"])
    except Exception as e:
        # cost_analysis is best-effort across jax versions, but a failure
        # must be visible — a silent null here hid a NameError for a round
        _log(f"flax: cost_analysis unavailable: {e!r}")

    _log("flax: warm steps")
    for i in range(3):                      # compile + warm
        state, loss = step(state, *batch(i))
    float(jax.device_get(loss))

    def timed_round():
        # forced completion via scalar fetch: through the remote-chip
        # tunnel block_until_ready returns before execution finishes,
        # which would time async dispatch instead of the train step
        nonlocal state
        tic = time.perf_counter()
        for i in range(N_BATCHES):
            state, loss = step(state, *batch(i))
        float(jax.device_get(loss))         # chained state forces all
        return N_BATCHES * BATCH / (time.perf_counter() - tic)

    return timed_round, flops


def main():
    import statistics

    import jax
    dev = jax.devices()[0]
    peak = PEAK_BF16.get(dev.device_kind)
    rng = np.random.RandomState(0)
    imgs, labels = _synthetic(rng)

    flax_round, flax_flops = setup_flax(imgs, labels)
    ours_round, ours_flops = setup_ours(imgs, labels)

    ratios, ours_rates, flax_rates = [], [], []
    for r in range(ROUNDS):
        f = flax_round()
        o = ours_round()
        _log(f"round {r}: ours {o:.1f} img/s, flax {f:.1f} img/s, "
             f"ratio {o / f:.2f}")
        flax_rates.append(f)
        ours_rates.append(o)
        ratios.append(o / f)
    ours_img_s = statistics.median(ours_rates)
    flax_img_s = statistics.median(flax_rates)
    ratio = statistics.median(ratios)

    # MFU from wall-clock is only a measurement when the wall clock is
    # actually dominated by device compute. Through the shared-chip tunnel
    # the step time can be >100x the device-side floor (flops/peak); in
    # that regime publishing flops/(peak*step_time) would present RPC
    # latency as a chip-utilization figure. Null it instead, with the
    # floor ratio recorded so the reader can see why.
    mfu_note = None

    def mfu(img_s, flops):
        nonlocal mfu_note
        if not (peak and flops):
            return None
        step_time = BATCH / img_s
        device_floor = flops / peak
        if step_time > 10 * device_floor:
            mfu_note = (f"wall step time {step_time:.2f}s is "
                        f"{step_time / device_floor:.0f}x the device-side "
                        f"floor {device_floor:.3f}s — transport-dominated; "
                        "wall-clock MFU withheld")
            return None
        return round(flops / (peak * step_time), 4)

    print(json.dumps({
        "metric": "resnet50_bf16_b256_train_img_per_sec_vs_flax_1chip",
        "value": round(ours_img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(ratio, 3),
        "flax_ref_img_s": round(flax_img_s, 2),
        "ratio_vs_flax": round(ratio, 3),
        "ratio_per_round": [round(r, 3) for r in ratios],
        "mfu_ours": mfu(ours_img_s, ours_flops),
        "mfu_flax": mfu(flax_img_s, flax_flops),
        "mfu_note": mfu_note,
        "flops_per_step_ours": ours_flops,
        "flops_per_step_flax": flax_flops,
        "device": dev.device_kind,
        "vs_p100_context": round(ours_img_s / REFERENCE_P100_IMG_S, 1),
        "env_note": "remote-tunneled shared chip: per-execution RPC "
                    "latency dominates absolute img/s (device-side "
                    "matmuls hit 67 TFLOP/s; D2H ~12 MB/s) and drifts "
                    "minute to minute, so the sides are timed in "
                    "interleaved rounds with forced completion and the "
                    "median per-round ratio is the signal",
    }))


if __name__ == "__main__":
    main()
